"""Synthetic + host-side data pipeline.

Capability parity with the reference dataloader layer (runtime/dataloader.py:
462-567 ``get_train_valid_test_data_iterators`` / ``get_batch`` / ``_loss_func``
and the random profiling dataset): deterministic synthetic token streams for
profiling/benchmarks and a batch iterator that yields numpy arrays ready for
``jax.device_put`` with a dp-sharded layout.

The mmap indexed Megatron dataset (+C++ index builder) is a later component
(SURVEY C13); this module defines the iterator contract it will plug into.

TPU note: the reference broadcasts batches within TP groups and zigzag-slices
for CP on each rank (utils.py:194-295). Under GSPMD there is one logical batch:
`jax.make_array_from_process_local_data` (or device_put with a NamedSharding)
places the dp-shard on each chip; TP/CP slicing happens inside the jitted
program via shardings, not in the loader.
"""

from __future__ import annotations

from typing import Dict, Iterator, Optional

import numpy as np

from hetu_galvatron_tpu.core.args_schema import CoreArgs, DataArgs, ModelArgs


class RandomTokenDataset:
    """Deterministic random tokens (reference's random dataset used by
    profiling runs and correctness tests, dataloader.py:462-524)."""

    def __init__(self, vocab_size: int, seq_length: int, size: int = 1024,
                 seed: int = 1234):
        self.vocab_size = vocab_size
        self.seq_length = seq_length
        self.size = size
        rng = np.random.RandomState(seed)
        # +1 token so input/label shift stays inside the sample
        self._data = rng.randint(
            0, vocab_size, (size, seq_length + 1), dtype=np.int32)

    def __len__(self) -> int:
        return self.size

    def __getitem__(self, idx: int) -> np.ndarray:
        return self._data[idx % self.size]


def make_batch(samples: np.ndarray) -> Dict[str, np.ndarray]:
    """[B, S+1] tokens -> {tokens, labels, loss_mask} (the reference's
    get_batch shift, dataloader.py:525-557)."""
    return {
        "tokens": samples[:, :-1].astype(np.int32),
        "labels": samples[:, 1:].astype(np.int32),
        "loss_mask": np.ones_like(samples[:, 1:], dtype=np.float32),
    }


def synthetic_batches(
    model: ModelArgs,
    global_batch_size: int,
    *,
    size: int = 1024,
    seed: int = 1234,
) -> Iterator[Dict[str, np.ndarray]]:
    """Infinite iterator of global batches of synthetic data."""
    ds = RandomTokenDataset(model.padded_vocab_size, model.seq_length,
                            size=size, seed=seed)
    i = 0
    while True:
        idx = [(i * global_batch_size + j) % len(ds)
               for j in range(global_batch_size)]
        yield make_batch(np.stack([ds[j] for j in idx]))
        i += 1


def get_data_iterator(
    args: CoreArgs, *, global_batch_size: Optional[int] = None
) -> Iterator[Dict[str, np.ndarray]]:
    """Entry point mirroring get_train_valid_test_data_iterators
    (dataloader.py:462)."""
    gbs = global_batch_size or args.parallel.global_train_batch_size
    data: DataArgs = args.data
    if data.dataset == "random":
        return synthetic_batches(args.model, gbs, seed=args.train.seed)
    raise NotImplementedError(
        "indexed datasets land with the C++ index builder (SURVEY C13)")
