"""Synthetic + host-side data pipeline.

Capability parity with the reference dataloader layer (runtime/dataloader.py:
462-567 ``get_train_valid_test_data_iterators`` / ``get_batch`` / ``_loss_func``
and the random profiling dataset): deterministic synthetic token streams for
profiling/benchmarks and a batch iterator that yields numpy arrays ready for
``jax.device_put`` with a dp-sharded layout.

The mmap indexed dataset (+C++ index builder) lives in
``data/indexed_dataset.py`` and plugs into :func:`get_data_iterator` via
``data.dataset=indexed``; BERT-family models get masked-LM batches instead of
the causal shift.

TPU note: the reference broadcasts batches within TP groups and zigzag-slices
for CP on each rank (utils.py:194-295). Under GSPMD there is one logical batch:
`jax.make_array_from_process_local_data` (or device_put with a NamedSharding)
places the dp-shard on each chip; TP/CP slicing happens inside the jitted
program via shardings, not in the loader.
"""

from __future__ import annotations

from typing import Dict, Iterator, Optional

import numpy as np

from hetu_galvatron_tpu.core.args_schema import CoreArgs, DataArgs, ModelArgs


class RandomTokenDataset:
    """Deterministic random tokens (reference's random dataset used by
    profiling runs and correctness tests, dataloader.py:462-524)."""

    def __init__(self, vocab_size: int, seq_length: int, size: int = 1024,
                 seed: int = 1234):
        self.vocab_size = vocab_size
        self.seq_length = seq_length
        self.size = size
        rng = np.random.RandomState(seed)
        # +1 token so input/label shift stays inside the sample
        self._data = rng.randint(
            0, vocab_size, (size, seq_length + 1), dtype=np.int32)

    def __len__(self) -> int:
        return self.size

    def __getitem__(self, idx: int) -> np.ndarray:
        return self._data[idx % self.size]


def make_batch(samples: np.ndarray) -> Dict[str, np.ndarray]:
    """[B, S+1] tokens -> {tokens, labels, loss_mask} (the reference's
    get_batch shift, dataloader.py:525-557)."""
    return {
        "tokens": samples[:, :-1].astype(np.int32),
        "labels": samples[:, 1:].astype(np.int32),
        "loss_mask": np.ones_like(samples[:, 1:], dtype=np.float32),
    }


def make_mlm_batch(
    samples: np.ndarray,
    vocab_size: int,
    rng: np.random.RandomState,
    *,
    mask_prob: float = 0.15,
    mask_token: Optional[int] = None,
    eligible: Optional[np.ndarray] = None,
) -> Dict[str, np.ndarray]:
    """[B, S] tokens -> BERT-style masked-LM batch: 15% of positions are
    selected (80% -> [MASK], 10% -> random token, 10% -> unchanged); labels
    are the originals and loss_mask covers only the selected positions.

    ``rng`` must advance between calls (the caller owns it) so each batch
    masks different positions. ``mask_token`` defaults to the top id of the
    (padded) vocab — real tokenizers should pass their [MASK] id; the padded
    rows the vocab-size rounding adds are a safe default home for it.
    ``eligible`` restricts which positions may be selected at all (the
    loader threads eod_mask_loss through it, so eod tokens are never masked
    or predicted)."""
    tokens = samples.astype(np.int32).copy()
    labels = samples.astype(np.int32)
    mask_token = vocab_size - 1 if mask_token is None else mask_token
    selected = rng.rand(*tokens.shape) < mask_prob
    if eligible is not None:
        selected &= np.asarray(eligible) > 0
    action = rng.rand(*tokens.shape)
    tokens[selected & (action < 0.8)] = mask_token
    random_ids = rng.randint(0, vocab_size, tokens.shape)
    swap = selected & (action >= 0.8) & (action < 0.9)
    tokens[swap] = random_ids[swap]
    return {
        "tokens": tokens,
        "labels": labels,
        "loss_mask": selected.astype(np.float32),
    }


def synthetic_batches(
    model: ModelArgs,
    global_batch_size: int,
    *,
    size: int = 1024,
    seed: int = 1234,
) -> Iterator[Dict[str, np.ndarray]]:
    """Infinite iterator of global batches of synthetic data."""
    ds = RandomTokenDataset(model.padded_vocab_size, model.seq_length,
                            size=size, seed=seed)
    i = 0
    while True:
        idx = [(i * global_batch_size + j) % len(ds)
               for j in range(global_batch_size)]
        yield make_batch(np.stack([ds[j] for j in idx]))
        i += 1


def skip_batches(it: Iterator, n: int) -> None:
    """Fast-forward ``n`` global batches — the full-state-resume replay of
    the data stream. Replaying (rather than seeking) keeps every stateful
    stage downstream of the raw reader — MLM masking RNG, packed-doc
    segmentation, zigzag permutation — in exactly the state the original
    run left it in. Rerun-machine wrappers are committed per batch so the
    replayed prefix does not pile up in the rewind cache."""
    advance = getattr(it, "advance", None)
    for _ in range(int(n)):
        next(it)
        if advance is not None:
            advance()


_SPLIT_INDEX = {"train": 0, "valid": 1, "test": 2}


def _zigzag_perm(seq: int, cp: int) -> np.ndarray:
    """Slot -> global-position permutation of the zigzag cp layout (rank r
    holds global half-blocks r and 2cp-1-r; ops/ring_attention.py
    zigzag_layout over arange)."""
    blocks = np.split(np.arange(seq), 2 * cp)
    order = []
    for r in range(cp):
        order.append(blocks[r])
        order.append(blocks[2 * cp - 1 - r])
    return np.concatenate(order)


def zigzag_cp_batches(it: Iterator[Dict[str, np.ndarray]], cp: int
                      ) -> Iterator[Dict[str, np.ndarray]]:
    """Apply the zigzag cp layout in the LOADER (reference get_batch zigzag
    slice, utils.py:295): every [B, S] field is permuted along the sequence
    and ``position_ids`` carry each slot's true global (or packed
    doc-relative) position so rope stays correct — ring layers then run
    ``data_zigzagged`` and skip the per-call layout reshard entirely."""
    perm = None
    for batch in it:
        S = batch["tokens"].shape[1]
        if S % (2 * cp):
            raise ValueError(
                f"cp_zigzag needs sequence {S} divisible by 2*cp = {2 * cp}")
        if perm is None or perm.size != S:
            perm = _zigzag_perm(S, cp)
        out = {}
        for k, v in batch.items():
            v = np.asarray(v)
            out[k] = (v[:, perm] if v.ndim >= 2 and v.shape[1] == S else v)
        if "position_ids" not in out:
            out["position_ids"] = np.broadcast_to(
                perm.astype(np.int32), batch["tokens"].shape).copy()
        yield out


def get_data_iterator(
    args: CoreArgs, *, global_batch_size: Optional[int] = None,
    split: str = "train", hpc=None,
) -> Iterator[Dict[str, np.ndarray]]:
    """One split's batch iterator (see
    :func:`get_train_valid_test_data_iterators` for the reference-shaped
    three-way entry point, runtime/dataloader.py:462). ``split`` selects
    the document range by the ``data.split`` ratios for indexed corpora;
    the synthetic dataset draws each split from a disjoint seed. Evaluation
    splits iterate in a stable (unshuffled) order."""
    gbs = global_batch_size or args.parallel.global_train_batch_size
    data: DataArgs = args.data
    meta: Dict = {}
    split_idx = _SPLIT_INDEX[split]
    if data.dataset == "random":
        it = synthetic_batches(args.model, gbs,
                               seed=args.train.seed + 101 * split_idx)
    elif data.dataset == "indexed":
        from hetu_galvatron_tpu.data.indexed_dataset import indexed_batches
        from hetu_galvatron_tpu.data.object_store import localize_prefix

        if not data.data_path:
            raise ValueError("data.dataset=indexed requires data.data_path")
        # s3:// prefixes download-once into the local cache (reference S3
        # indexed datasets, indexed_dataset.py:506); local paths unchanged
        data = data.model_copy(
            update={"data_path": [localize_prefix(p)
                                  for p in data.data_path]})
        meta = corpus_meta(data.data_path)
        if meta.get("vocab_size", 0) > args.model.padded_vocab_size:
            raise ValueError(
                f"corpus tokenizer vocab {meta['vocab_size']} exceeds model "
                f"padded vocab {args.model.padded_vocab_size}")
        it = indexed_batches(data.data_path, args.model.seq_length, gbs,
                             seed=args.train.seed, split=data.split,
                             split_index=split_idx,
                             shuffle=split == "train")
        if (data.eod_mask_loss and meta.get("eod_id") is not None
                and args.model.model_type != "bert"):
            # bert handles eod inside mlm_batches (the causal-shifted
            # loss_mask here would be off by one for MLM positions)
            it = eod_masked_batches(it, meta["eod_id"])
    else:
        raise ValueError(f"unknown dataset kind {data.dataset}")
    if data.reset_position_ids or data.reset_attention_mask:
        if args.model.model_type in ("bert", "t5"):
            raise NotImplementedError(
                "reset_position_ids/reset_attention_mask are causal-LM "
                "packing flags (bert/t5 batches have no packed documents)")
        if meta.get("eod_id") is None:
            raise ValueError(
                "reset_position_ids/reset_attention_mask need document "
                "boundaries: use data.dataset=indexed with an eod-emitting "
                "tokenizer (preprocess_data writes eod_id to the sidecar)")
        it = packed_doc_batches(
            it, meta["eod_id"],
            reset_position_ids=data.reset_position_ids,
            reset_attention_mask=data.reset_attention_mask)
    if args.model.model_type == "bert":
        # encoders train on the MLM objective, never the causal shift
        # (bidirectional attention would leak shifted labels)
        return mlm_batches(it, args.model, seed=args.train.seed,
                           mask_token=meta.get("mask_id"),
                           eod_id=(meta.get("eod_id")
                                   if data.eod_mask_loss else None))
    if args.model.model_type == "t5":
        return seq2seq_batches(it)
    if hpc is not None and getattr(hpc, "cp_zigzag", False):
        # plan validated by get_hybrid_parallel_config: uniform cp, causal
        it = zigzag_cp_batches(it, hpc.layers[0].cp_size)
    return it


def get_train_valid_test_data_iterators(
    args: CoreArgs, *, global_batch_size: Optional[int] = None, hpc=None,
):
    """(train, valid, test) iterators (reference
    get_train_valid_test_data_iterators, runtime/dataloader.py:462). The
    eval iterators are built lazily only when train.eval_interval and
    eval_iters are both set — an empty valid/test split must not fail a
    training-only run."""
    import sys

    train_it = get_data_iterator(args, global_batch_size=global_batch_size,
                                 split="train", hpc=hpc)
    valid_it = test_it = None
    if args.train.eval_interval and args.train.eval_iters:
        for name in ("valid", "test"):
            try:
                it = get_data_iterator(
                    args, global_batch_size=global_batch_size, split=name,
                    hpc=hpc)
            except ValueError as e:
                # an undersized split must degrade eval, not crash a run
                # after the training compute is spent (the small-corpus case
                # under the default 969/30/1 ratios)
                print(f"warning: {name} eval disabled: {e}",
                      file=sys.stderr)
                it = None
            if name == "valid":
                valid_it = it
            else:
                test_it = it
    return train_it, valid_it, test_it


def corpus_meta(paths) -> Dict:
    """Read the preprocess CLI's ``<prefix>.meta.json`` sidecar (tokenizer
    geometry: vocab_size / eod_id). Multiple blended corpora must agree."""
    import json
    import os

    paths = [paths] if isinstance(paths, str) else list(paths)
    metas = []
    for p in paths:
        mp = p + ".meta.json"
        if os.path.exists(mp):
            with open(mp) as f:
                metas.append(json.load(f))
    if not metas:
        return {}
    first = metas[0]
    for m in metas[1:]:
        if (m.get("vocab_size"), m.get("eod_id")) != (
                first.get("vocab_size"), first.get("eod_id")):
            raise ValueError(
                "blended corpora were tokenized with different tokenizers: "
                f"{metas}")
    return first


def eod_masked_batches(it: Iterator[Dict[str, np.ndarray]], eod_id: int
                       ) -> Iterator[Dict[str, np.ndarray]]:
    """Zero the loss where the INPUT token is end-of-document (reference
    eod_mask_loss, utils.py get_ltor_masks_and_position_ids): the eod
    position would otherwise be trained to predict the NEXT document's
    first token. Predicting eod itself (label == eod) stays in the loss —
    the model must learn to emit it."""
    for batch in it:
        batch = dict(batch)
        batch["loss_mask"] = (batch["loss_mask"]
                              * (batch["tokens"] != eod_id))
        yield batch


def packed_doc_fields(tokens: np.ndarray, eod_id: int, *,
                      reset_position_ids: bool, reset_attention_mask: bool
                      ) -> Dict[str, np.ndarray]:
    """Per-token position/segment ids for packed multi-document samples
    (reference reset_position_ids / reset_attention_mask, Megatron
    get_ltor_masks_and_position_ids): a document starts AFTER each eod
    token; positions restart at 0 there and segment ids increment so
    attention can be block-diagonalized per document."""
    doc_starts = np.zeros_like(tokens, dtype=np.int64)
    doc_starts[:, 1:] = (tokens[:, :-1] == eod_id)
    segments = np.cumsum(doc_starts, axis=1)
    out: Dict[str, np.ndarray] = {}
    if reset_attention_mask:
        out["segment_ids"] = segments.astype(np.int32)
    if reset_position_ids:
        pos = np.arange(tokens.shape[1], dtype=np.int64)[None, :]
        # position of each document's first token, broadcast along the doc
        starts = np.where(doc_starts.astype(bool), pos, 0)
        doc_start_pos = np.maximum.accumulate(starts, axis=1)
        out["position_ids"] = (pos - doc_start_pos).astype(np.int32)
    return out


def packed_doc_batches(it: Iterator[Dict[str, np.ndarray]], eod_id: int, *,
                       reset_position_ids: bool, reset_attention_mask: bool
                       ) -> Iterator[Dict[str, np.ndarray]]:
    for batch in it:
        batch = dict(batch)
        batch.update(packed_doc_fields(
            batch["tokens"], eod_id,
            reset_position_ids=reset_position_ids,
            reset_attention_mask=reset_attention_mask))
        yield batch


def seq2seq_batches(it: Iterator[Dict[str, np.ndarray]]
                    ) -> Iterator[Dict[str, np.ndarray]]:
    """Causal batches -> seq2seq: the first half of each sample becomes the
    encoder source, the second half the (shifted) decoder target."""
    for batch in it:
        tokens = batch["tokens"]
        half = tokens.shape[1] // 2
        # tokens/labels are already the one-step-shifted pair, so slicing
        # both at `half` keeps decoder input i aligned with label i+1
        yield {
            "enc_tokens": tokens[:, :half],
            "tokens": tokens[:, half:],
            "labels": batch["labels"][:, half:],
            "loss_mask": batch["loss_mask"][:, half:],
        }


def mlm_batches(it: Iterator[Dict[str, np.ndarray]], model: ModelArgs,
                seed: int, mask_token: Optional[int] = None,
                eod_id: Optional[int] = None
                ) -> Iterator[Dict[str, np.ndarray]]:
    """``eod_id`` excludes end-of-document tokens from MLM selection (the
    bert leg of data.eod_mask_loss — without this the flag would be a
    silent no-op for encoders)."""
    rng = np.random.RandomState(seed + 1)
    for batch in it:
        eligible = (batch["tokens"] != eod_id) if eod_id is not None else None
        yield make_mlm_batch(batch["tokens"], model.padded_vocab_size, rng,
                             mask_token=mask_token, eligible=eligible)
