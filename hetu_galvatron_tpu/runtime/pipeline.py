"""Pipeline-parallel engine: GPipe and 1F1B schedules over stage submeshes.

Capability parity with the reference pipeline engine
(runtime/pipeline/pipeline.py:43 ``PipelineParallel``, :729-905 gpipe,
:386-712 pipedream-flush/1F1B, stage slicing :104-106, tied-embedding grad
all-reduce :708-710,1042), re-designed for the single-controller JAX runtime:

* Each pipeline stage is its OWN jitted GSPMD program over a **submesh** of
  the global device set (the stage's slice of chips, with the binary d-axes
  of runtime/mesh.py). Per-layer tp/dp/ZeRO/remat heterogeneity inside a
  stage reuses the exact same sharding lowering as the pp=1 path — and
  uneven ``pp_division`` is natural because stages are separate programs.
* Microbatch activations travel between submeshes with `jax.device_put`
  (ICI DMA on TPU) — the reference's batched NCCL isend/irecv
  (pipeline.py:1091-1140) becomes a sharding-to-sharding transfer.
* The host sequences the schedule; JAX async dispatch overlaps stages
  (stage s microbatch m and stage s+1 microbatch m-1 run concurrently on
  disjoint chips). GPipe = all-forward-then-all-backward; 1F1B = warmup of
  (P - s) forwards per stage then alternating 1F1B steady state, which
  bounds live activations per stage exactly like the reference.
* Backward recomputes the stage forward (per-stage remat) via `jax.vjp`,
  so stored state per in-flight microbatch is just the stage input.
* Tied embeddings: the last stage holds a transposed copy of wte; after
  each step both copies' grads are summed across the two stages (the
  reference's finalize_wte_grads over the embedding group) and both are
  updated with identical elementwise Adam math, keeping them in sync.
* Encoder-decoder (t5) pipelines: the combined enc+dec layer sequence is
  stage-sliced like the reference's any-arch PipeSequential
  (pipeline.py:1592). The inter-stage activation is a PAIR ``(a, b)``:
  ``a`` is the encoder stream (then the encoder memory once the stage
  holding the last encoder layer applies enc_norm) and ``b`` is the decoder
  stream. Stage 0 embeds BOTH token streams with the shared embedding, so
  the decoder stream rides through encoder stages as a passthrough — wte
  gradients from both streams accumulate on stage 0 with no extra tied-copy
  reconciliation; memory cotangents flow back through the same pair.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from hetu_galvatron_tpu.core.args_schema import ModelArgs, TrainArgs
from hetu_galvatron_tpu.models import modules as M
from hetu_galvatron_tpu.runtime.hybrid_config import HybridParallelConfig
from hetu_galvatron_tpu.runtime.mesh import (
    LayerSharding,
    build_mesh,
    device_array,
    lower_strategy,
    lower_vocab_strategy,
    spec_tree as _spec_tree,
)
from hetu_galvatron_tpu.observability.registry import get_registry
from hetu_galvatron_tpu.observability.trace_analysis import (
    maybe_record_jit_cost,
)
from hetu_galvatron_tpu.observability.tracing import span
from hetu_galvatron_tpu.runtime.optimizer import make_lr_schedule

Params = Dict[str, Any]


def _pipeline_optimizer(train: TrainArgs) -> optax.GradientTransformation:
    """Adam+wd+schedule WITHOUT the global-norm clip — pipeline clipping is
    global across stages, so the scale factor is applied explicitly by the
    engine (reference clip_grad_norm handles sharded params the same way,
    optimizer/utils.py:14)."""
    from hetu_galvatron_tpu.runtime.optimizer import (
        _decay_mask,
        partition_expert_bias,
    )

    chain = [optax.scale_by_adam(b1=train.adam_beta1, b2=train.adam_beta2,
                                 eps=train.adam_eps)]
    if train.weight_decay:
        chain.append(optax.add_decayed_weights(train.weight_decay,
                                               mask=_decay_mask))
    chain.append(optax.scale_by_learning_rate(make_lr_schedule(train)))
    return partition_expert_bias(optax.chain(*chain))


@dataclass
class _Stage:
    index: int
    mesh: Mesh
    layer_range: Tuple[int, int]  # [lo, hi) decoder-layer indices
    shardings: List[LayerSharding]  # per decoder layer in this stage
    vocab: Optional[LayerSharding]  # set on first/last stage
    has_embed: bool
    has_head: bool
    # encoder-decoder (t5) only:
    enc_layer_range: Tuple[int, int] = (0, 0)  # [lo, hi) encoder-layer idxs
    enc_shardings: List[LayerSharding] = None
    has_enc_norm: bool = False


class PipelineEngine:
    """Stage-sliced hybrid-parallel training with GPipe / 1F1B schedules."""

    def __init__(
        self,
        cfg: ModelArgs,
        hpc: HybridParallelConfig,
        train: TrainArgs,
        devices: Optional[List] = None,
        *,
        compute_dtype=jnp.bfloat16,
        dcn_slices: int = 1,
        tp_overlap: bool = False,
        use_flash: Optional[bool] = None,
        flash_interpret: bool = False,
        hier_dp: bool = False,
        hier_bucket_mb: float = 0.0,
    ):
        self.cfg = cfg
        self.hpc = hpc
        self.train = train
        self.compute_dtype = compute_dtype
        self._hier_bucket_mb = float(hier_bucket_mb)
        # hierarchical dp gradient reduction (ops/hier_reduce.py): stage
        # backwards run per dp LANE (vmap over the lane-split microbatch)
        # so grads accumulate lane-stacked across the schedule, and ONE
        # per-stage three-collective reduce runs before the tied-embedding
        # exchange / global clip — which therefore stay unchanged.
        self.hier_dp = bool(hier_dp)
        self._dcn_slices = dcn_slices
        self._axes_tree: Optional[Params] = None
        if self.hier_dp:
            from hetu_galvatron_tpu.analysis.eligibility import (
                HIER_KERNEL_REASON,
                plan_hier_dp_reason,
            )

            _reason = plan_hier_dp_reason(cfg, hpc)
            if _reason is None and tp_overlap:
                _reason = HIER_KERNEL_REASON
            if _reason is None and any(s.cp_size > 1 or s.sp
                                       for s in hpc.layers):
                # the stage programs keep their ring-cp / ulysses-a2a
                # shard_map kernels (unlike the pp=1 SPMD path, which
                # swaps them for the GSPMD core under the lane vmap)
                _reason = HIER_KERNEL_REASON
            if _reason is None and (use_flash or (
                    use_flash is None and cfg.use_flash_attn
                    and jax.devices()[0].platform == "tpu")):
                _reason = HIER_KERNEL_REASON
            if _reason is not None:
                raise ValueError(f"hier_dp unsupported: {_reason}")
        # overlapped-TP projection matmuls inside the stage programs
        # (ops/overlap.py); eligible layers only — same dispatch as the
        # SPMD path's tp_overlap_overrides, per stage submesh
        self.tp_overlap = tp_overlap
        # attention-impl override knobs for parity drills: use_flash=None
        # keeps the cfg/platform default; flash_interpret runs the Pallas
        # kernels in interpret mode (CPU meshes)
        self._use_flash = use_flash
        self._flash_interpret = flash_interpret
        self.pp = hpc.pp_deg
        if self.pp < 2:
            # pp=1 routes through the SPMD path (cli/train_dist.py). The
            # engine's stage-0 backward differentiates w.r.t. its input —
            # with a single fused embed+head stage that input is integer
            # tokens — and the tied-embedding grad reconciliation assumes
            # separate first/last stages (ADVICE r2: a pp=1 engine would
            # silently untie wte/whead).
            raise ValueError(
                "PipelineEngine needs pp_deg >= 2; use make_spmd_train_step "
                "for pp=1")
        self.is_t5 = cfg.model_type == "t5"
        devices = list(devices if devices is not None else jax.devices())
        if len(devices) < hpc.world_size:
            raise ValueError(
                f"need {hpc.world_size} devices, have {len(devices)}")
        # DCN-aware global arrangement BEFORE carving stage groups: with
        # dcn_slices > 1 the pp axis (and outer dp) land on slice
        # boundaries, so each stage's submesh stays ICI-local
        devices = list(device_array(
            hpc.world_size, self.pp, devices[:hpc.world_size],
            dcn_slices).flat)
        per_stage = hpc.world_size // self.pp
        self.tx = _pipeline_optimizer(train)
        self.stages: List[_Stage] = []
        n_enc = hpc.num_encoder_layers
        # interleaved virtual stages: pp_division has pp*vpp chunks; chunk c
        # runs on physical device group c % pp (Megatron round-robin), so
        # each group hosts vpp non-contiguous model chunks and the
        # warmup/cooldown bubble shrinks ~vpp-fold. vpp=1 degenerates to the
        # plain one-chunk-per-group layout.
        self.vpp = max(getattr(hpc, "vpp_deg", 1), 1)
        group_meshes = []
        for g in range(self.pp):
            sub = devices[g * per_stage:(g + 1) * per_stage]
            group_meshes.append(build_mesh(per_stage, 1, devices=sub))
        n_chunks = self.pp * self.vpp
        lo = 0
        for s in range(n_chunks):
            mesh = group_meshes[s % self.pp]
            hi = lo + hpc.pp_division[s]
            # combined-stack slicing: hpc.layers = enc layers then dec layers
            enc_lo, enc_hi = min(lo, n_enc), min(hi, n_enc)
            dec_lo, dec_hi = max(lo, n_enc) - n_enc, max(hi, n_enc) - n_enc
            enc_shardings = [lower_strategy(st, mesh)
                             for st in hpc.layers[enc_lo:enc_hi]]
            shardings = [lower_strategy(st, mesh)
                         for st in hpc.layers[n_enc + dec_lo:n_enc + dec_hi]]
            vocab = lower_vocab_strategy(hpc.vocab, mesh, hpc.default_dp_type)
            has_enc_norm = self.is_t5 and (
                enc_lo <= n_enc - 1 < enc_hi or (n_enc == 0 and s == 0))
            self.stages.append(_Stage(
                index=s, mesh=mesh, layer_range=(dec_lo, dec_hi),
                shardings=shardings, vocab=vocab, has_embed=(s == 0),
                has_head=(s == n_chunks - 1),
                enc_layer_range=(enc_lo, enc_hi),
                enc_shardings=enc_shardings, has_enc_norm=has_enc_norm))
            lo = hi
        # ALL stage/step jits are built lazily on first use (like the eval
        # jits always were): an eval-only engine never constructs backward
        # or update programs, an untied plan never constructs the tied-grad
        # transpose, and plans that never train build nothing at all.
        self._lazy_jits: Dict[str, Any] = {}
        self._eval_jits = None  # built on first eval_step (dropout off)
        # one-shot cost/* recording: resolved once per step (train_step),
        # not per microbatch — the schedule's inner loop is exactly what
        # pipeline_dispatch_bench measures, so it must stay free of
        # registry lookups after the first recorded step
        self._jit_cost_done = False
        self._record_costs = False

    def _jit(self, name: str, build) -> Any:
        """Construct-on-first-use cache for the engine's jitted helpers."""
        if name not in self._lazy_jits:
            self._lazy_jits[name] = build()
        return self._lazy_jits[name]

    @property
    def _fwd_jits(self) -> List[Optional[Callable]]:
        return self._jit("fwd", lambda: [self._make_fwd(st)
                                         for st in self.stages])

    @property
    def _bwd_jits(self) -> List[Callable]:
        return self._jit("bwd", lambda: [self._make_bwd(st)
                                         for st in self.stages])

    @property
    def _update_jits(self) -> List[Callable]:
        return self._jit("update", lambda: [self._make_update(st)
                                            for st in self.stages])

    @property
    def _transpose_jit(self) -> Callable:
        return self._jit("transpose", lambda: jax.jit(jnp.transpose))

    @property
    def _gnorm_jit(self) -> Callable:
        # expert_bias maintenance pseudo-grads stay out of the clip norm,
        # matching the SPMD path (clip_by_global_norm lives inside the
        # multi_transform adam branch, which never sees bias leaves)
        return self._jit("gnorm", lambda: jax.jit(
            lambda g: sum(
                jnp.sum(jnp.square(x.astype(jnp.float32)))
                for path, x in jax.tree_util.tree_leaves_with_path(g)
                if not path or "expert_bias" not in str(path[-1]))))

    @property
    def _clip_jit(self) -> Callable:
        clip = self.train.clip_grad
        return self._jit("clip", lambda: jax.jit(
            lambda sq: (jnp.sqrt(sq),
                        jnp.minimum(1.0, clip / (jnp.sqrt(sq) + 1e-12))
                        if clip and clip > 0 else jnp.ones((), jnp.float32))))

    # ------------------------------------------------------------------
    # params / optimizer state
    # ------------------------------------------------------------------

    def stage_param_axes(self, axes: Params, s: int) -> Params:
        st = self.stages[s]
        lo, hi = st.layer_range
        out: Params = {"layers": tuple(axes["layers"][lo:hi])}
        if self.is_t5:
            elo, ehi = st.enc_layer_range
            out["enc_layers"] = tuple(axes["enc_layers"][elo:ehi])
            if st.has_enc_norm:
                out["enc_norm"] = axes["enc_norm"]
        if st.has_embed:
            out["embed"] = axes["embed"]
        if st.has_head:
            out["prenorm"] = axes["prenorm"]
            if self.cfg.tie_word_embeddings:
                # tied copy replaces the wte reference; any extra head params
                # (bert's MLM transform wt/bt/ln/bias) ride along
                out["head"] = {**axes["head"], "whead": ("embed", "vocab")}
            else:
                out["head"] = axes["head"]
        return out

    def stage_param_specs(self, axes: Params, s: int, opt: bool = False
                          ) -> Params:
        st = self.stages[s]
        saxes = self.stage_param_axes(axes, s)
        out: Params = {"layers": tuple(
            _spec_tree(a, sh, opt)
            for a, sh in zip(saxes["layers"], st.shardings))}
        if "enc_layers" in saxes:
            out["enc_layers"] = tuple(
                _spec_tree(a, sh, opt)
                for a, sh in zip(saxes["enc_layers"], st.enc_shardings))
        for k in ("embed", "prenorm", "head", "enc_norm"):
            if k in saxes:
                out[k] = _spec_tree(saxes[k], st.vocab, opt)
        return out

    def split_params(self, params: Params, axes: Params) -> List[Params]:
        """Slice a full (host/single-device) params tree into per-stage
        sharded trees (reference stage slicing, pipeline.py:104-106)."""
        self._axes_tree = axes  # the hier reducers' grad specs need it
        out = []
        for s, st in enumerate(self.stages):
            lo, hi = st.layer_range
            sp: Params = {"layers": tuple(params["layers"][lo:hi])}
            if self.is_t5:
                elo, ehi = st.enc_layer_range
                sp["enc_layers"] = tuple(params["enc_layers"][elo:ehi])
                if st.has_enc_norm:
                    sp["enc_norm"] = params["enc_norm"]
            if st.has_embed:
                sp["embed"] = params["embed"]
            if st.has_head:
                sp["prenorm"] = params["prenorm"]
                if self.cfg.tie_word_embeddings:
                    sp["head"] = {**params["head"],
                                  "whead": jnp.asarray(params["embed"]["wte"]).T}
                else:
                    sp["head"] = params["head"]
            specs = self.stage_param_specs(axes, s)
            out.append(jax.tree.map(
                lambda p, spec: jax.device_put(
                    p, NamedSharding(st.mesh, spec)), sp, specs))
        return out

    def merge_params(self, stage_params: List[Params]) -> Params:
        """Reassemble the full params tree (host) — for tests/checkpointing."""
        layers: List[Params] = []
        for sp in stage_params:
            layers.extend(jax.device_get(list(sp["layers"])))
        full: Params = {"layers": tuple(layers)}
        if self.is_t5:
            enc: List[Params] = []
            for sp in stage_params:
                enc.extend(jax.device_get(list(sp["enc_layers"])))
            full["enc_layers"] = tuple(enc)
            for sp, st in zip(stage_params, self.stages):
                if st.has_enc_norm:
                    full["enc_norm"] = jax.device_get(sp["enc_norm"])
        full["embed"] = jax.device_get(stage_params[0]["embed"])
        last = stage_params[-1]
        full["prenorm"] = jax.device_get(last["prenorm"])
        if self.cfg.tie_word_embeddings:
            full["head"] = jax.device_get(
                {k: v for k, v in last["head"].items() if k != "whead"})
        else:
            full["head"] = jax.device_get(last["head"])
        return full

    def init_opt(self, stage_params: List[Params], axes: Params
                 ) -> List[Any]:
        out = []
        for s, (sp, st) in enumerate(zip(stage_params, self.stages)):
            ospecs = self._opt_state_specs(sp, axes, s)
            init = jax.jit(self.tx.init, out_shardings=ospecs)
            out.append(init(sp))
        return out

    def _opt_state_specs(self, sp: Params, axes: Params, s: int):
        from hetu_galvatron_tpu.parallel.spmd import opt_state_specs

        opt_pspecs = self.stage_param_specs(axes, s, opt=True)
        specs = opt_state_specs(self.tx, sp, opt_pspecs)
        mesh = self.stages[s].mesh
        return jax.tree.map(
            lambda spec: NamedSharding(mesh, spec), specs,
            is_leaf=lambda x: isinstance(x, P))

    # ------------------------------------------------------------------
    # stage programs
    # ------------------------------------------------------------------

    def _stage_grad_specs(self, axes: Params, s: int) -> Params:
        """Grad-layout specs for the hierarchical reducer: the stage's
        param specs with ZeRO-3 dp-sharding overridden OFF (the reduction's
        lane axis owns the dp mesh axes — ops/hier_reduce.py)."""
        st = self.stages[s]
        saxes = self.stage_param_axes(axes, s)
        is_axes = lambda x: (isinstance(x, tuple)
                             and all(isinstance(a, str) for a in x))
        no3 = lambda a, sh: jax.tree.map(
            lambda la: sh.param_spec(la, zero3_override=False), a,
            is_leaf=is_axes)
        out: Params = {"layers": tuple(
            no3(a, sh) for a, sh in zip(saxes["layers"], st.shardings))}
        for k in ("embed", "prenorm", "head"):
            if k in saxes:
                out[k] = no3(saxes[k], st.vocab)
        return out

    def _make_hier_reduce(self, s: int) -> Callable:
        """One stage's jitted hierarchical reduce: lane-stacked grad tree
        -> summed tree, three explicit collectives on the stage submesh."""
        from hetu_galvatron_tpu.ops.hier_reduce import HierDpReducer
        from hetu_galvatron_tpu.runtime.mesh import (
            axes_size,
            hier_cross_degree,
        )

        if self._axes_tree is None:
            raise RuntimeError("split_params must run before the first "
                               "hier_dp train_step (it records the logical "
                               "axes tree the reducer specs derive from)")
        st = self.stages[s]
        # uniform-plan gate: every layer shares one dp assignment; stages
        # without decoder layers still lower the plan's first layer
        sh0 = (st.shardings[0] if st.shardings
               else lower_strategy(self.hpc.layers[0], st.mesh))
        dp_deg = axes_size(st.mesh, sh0.dp_axes)
        # slice absorption is pp-first (mesh.dcn_factor_shape): the stage
        # groups already sit on slice boundaries, the leftover slices split
        # each stage's dp internally
        cross = hier_cross_degree(self.pp, dp_deg, self._dcn_slices)
        reducer = HierDpReducer(
            mesh=st.mesh, dp_axes=sh0.dp_axes, cross=cross,
            intra=dp_deg // cross,
            specs=self._stage_grad_specs(self._axes_tree, s),
            bucket_mb=self._hier_bucket_mb)
        return jax.jit(reducer.reduce)

    @property
    def _hier_jits(self) -> List[Callable]:
        return self._jit("hier", lambda: [self._make_hier_reduce(s)
                                          for s in range(len(self.stages))])

    def _stage_apply(self, st: _Stage, sp: Params, x: jax.Array,
                     labels=None, loss_mask=None, dropout_rng=None,
                     position_ids=None, segment_ids=None):
        """Non-head stages return (x, stage_aux); the head stage returns
        ce_loss + its own aux (MoE auxiliary losses contribute per stage).
        ``dropout_rng`` is the per-(microbatch, stage) key; the schedule
        passes the SAME key to a microbatch's forward and backward so the
        backward's remat recomputation reuses the forward's masks.

        ``position_ids`` / ``segment_ids`` [B, S] are the packed-document
        fields (reset_position_ids / reset_attention_mask): the single
        controller places them on every stage's submesh directly, where the
        reference ships them through multi-tensor p2p transfers
        (pipeline.py:1140 _communicate)."""
        from hetu_galvatron_tpu.models.moe import apply_moe_decoder_layer

        cfg = self.cfg

        def layer_rng(j):
            return M.fold_dropout_rng(dropout_rng, cfg, j)

        if st.has_embed:
            x = M.apply_embedding(sp["embed"], x, cfg,
                                  compute_dtype=self.compute_dtype,
                                  dropout_rng=layer_rng(M.DROPOUT_STREAM_EMBED),
                                  position_ids=position_ids)
        rope = None
        if cfg.position_embedding_type == "rope":
            cos, sin = M.rope_cos_sin(x.shape[1], cfg.head_dim,
                                      cfg.rope_theta,
                                      scaling=cfg.rope_scaling)
            if position_ids is not None:
                # packed samples: gather per-token rows -> [B, S, D/2]
                cos, sin = cos[position_ids], sin[position_ids]
            rope = (cos, sin)
        from hetu_galvatron_tpu.parallel.spmd import attention_overrides

        overrides = attention_overrides(
            st.shardings, st.mesh,
            use_flash=(self._use_flash if self._use_flash is not None
                       else (None if cfg.use_flash_attn else False)),
            cp_zigzag=getattr(self.hpc, "cp_zigzag", False),
            flash_interpret=self._flash_interpret)
        if self.tp_overlap:
            from hetu_galvatron_tpu.parallel.spmd import tp_overlap_overrides

            # MoE detection must look at THIS stage's param slice — the
            # global moe_layer_freq alternation is invisible to stage-local
            # indices
            ov, _ = tp_overlap_overrides(
                st.shardings, st.mesh, cfg,
                is_moe_layer_fn=lambda _c, j: "moe" in sp["layers"][j])
            for j, kw in ov.items():
                overrides[j] = {**kw, **overrides.get(j, {})}
        seg_kw = ({"segment_ids": segment_ids}
                  if segment_ids is not None else {})
        aux_total = jnp.zeros((), jnp.float32)
        for j, lp in enumerate(sp["layers"]):
            sh = st.shardings[j]
            x = jax.lax.with_sharding_constraint(
                x, NamedSharding(st.mesh, sh.act_spec()))
            if "moe" in lp:
                fn = partial(apply_moe_decoder_layer, cfg=cfg, rope=rope,
                             compute_dtype=self.compute_dtype,
                             dropout_rng=layer_rng(j),
                             **seg_kw, **overrides.get(j, {}))
            else:
                base = partial(M.apply_decoder_layer, cfg=cfg, rope=rope,
                               compute_dtype=self.compute_dtype,
                               dropout_rng=layer_rng(j),
                               **seg_kw, **overrides.get(j, {}))
                fn = lambda p, h, b=base: (b(p, h),
                                           jnp.zeros((), jnp.float32), {})
            if sh.checkpoint:
                fn = M.remat(fn, cfg)
            # per-layer router stats are an spmd-path feature; the stage
            # programs fold only the aux scalar into the loss
            x, aux, _ = fn(lp, x)
            aux_total = aux_total + aux
        if not st.has_head:
            # a stage may carry zero decoder layers (embed-only stage 0)
            sh = st.shardings[-1] if st.shardings else st.vocab
            return jax.lax.with_sharding_constraint(
                x, NamedSharding(st.mesh, sh.act_spec())), aux_total
        x = jax.lax.with_sharding_constraint(
            x, NamedSharding(st.mesh, st.vocab.act_spec()))
        x = M.apply_norm(sp["prenorm"], x, cfg)
        # sp["head"] always carries whead on this stage (split_params puts
        # the transposed tied copy there), so apply_lm_head uses it directly
        logits = M.apply_lm_head(sp["head"], x, cfg,
                                 compute_dtype=self.compute_dtype)
        return M.cross_entropy_loss(logits, labels, loss_mask) + aux_total

    def _stage_apply_t5(self, st: _Stage, sp: Params, carry,
                        labels=None, loss_mask=None, dropout_rng=None):
        """Encoder-decoder stage program. ``carry`` is (enc_tokens,
        dec_tokens) on the embed stage, else the (a, b) activation pair —
        a = encoder stream / memory [B,S,H], b = decoder stream [B,T,H].
        Same contract as :meth:`_stage_apply`: non-head stages return
        (carry, aux); the head stage returns the CE loss."""
        from hetu_galvatron_tpu.models.encdec import apply_cross_decoder_layer
        from hetu_galvatron_tpu.parallel.spmd import attention_overrides

        cfg = self.cfg

        def layer_rng(j):
            return M.fold_dropout_rng(dropout_rng, cfg, j)

        if st.has_embed:
            enc_tok, dec_tok = carry
            a = M.apply_embedding(sp["embed"], enc_tok, cfg,
                                  compute_dtype=self.compute_dtype,
                                  dropout_rng=layer_rng(M.DROPOUT_STREAM_EMBED_ENC))
            b = M.apply_embedding(sp["embed"], dec_tok, cfg,
                                  compute_dtype=self.compute_dtype,
                                  dropout_rng=layer_rng(M.DROPOUT_STREAM_EMBED))
        else:
            a, b = carry
        rope_enc = rope_dec = None
        if cfg.position_embedding_type == "rope":
            rope_enc = M.rope_cos_sin(a.shape[1], cfg.head_dim, cfg.rope_theta,
                                      scaling=cfg.rope_scaling)
            rope_dec = M.rope_cos_sin(b.shape[1], cfg.head_dim, cfg.rope_theta,
                                      scaling=cfg.rope_scaling)
        use_flash = None if cfg.use_flash_attn else False
        enc_over = attention_overrides(st.enc_shardings, st.mesh,
                                       use_flash=use_flash)
        dec_over = attention_overrides(st.shardings, st.mesh,
                                       use_flash=use_flash, with_cross=True)
        for j, lp in enumerate(sp["enc_layers"]):
            sh = st.enc_shardings[j]
            a = jax.lax.with_sharding_constraint(
                a, NamedSharding(st.mesh, sh.act_spec()))
            kwargs = dict(rope=rope_enc, compute_dtype=self.compute_dtype,
                          causal=False, dropout_rng=layer_rng(M.DROPOUT_STREAM_ENC + j),
                          **enc_over.get(j, {}))
            kwargs.pop("cross_sdpa_fn", None)
            fn = partial(M.apply_decoder_layer, cfg=cfg, **kwargs)
            if sh.checkpoint:
                fn = M.remat(fn, cfg)
            a = fn(lp, a)
        if st.has_enc_norm:
            a = M.apply_norm(sp["enc_norm"], a, cfg)
        for j, lp in enumerate(sp["layers"]):
            sh = st.shardings[j]
            b = jax.lax.with_sharding_constraint(
                b, NamedSharding(st.mesh, sh.act_spec()))
            kwargs = dict(rope=rope_dec, compute_dtype=self.compute_dtype,
                          dropout_rng=layer_rng(j), **dec_over.get(j, {}))
            fn = partial(apply_cross_decoder_layer, cfg=cfg, **kwargs)
            if sh.checkpoint:
                fn = M.remat(fn, cfg)
            b = fn(lp, b, a)
        aux = jnp.zeros((), jnp.float32)  # t5 stacks carry no MoE aux
        if not st.has_head:
            spec_a, spec_b = self._carry_specs(st, out=True)
            return (jax.lax.with_sharding_constraint(
                        a, NamedSharding(st.mesh, spec_a)),
                    jax.lax.with_sharding_constraint(
                        b, NamedSharding(st.mesh, spec_b))), aux
        b = jax.lax.with_sharding_constraint(
            b, NamedSharding(st.mesh, st.vocab.act_spec()))
        b = M.apply_norm(sp["prenorm"], b, cfg)
        logits = M.apply_lm_head(sp["head"], b, cfg,
                                 compute_dtype=self.compute_dtype)
        return M.cross_entropy_loss(logits, labels, loss_mask) + aux

    def _carry_specs(self, st: _Stage, *, out: bool) -> Tuple[P, P]:
        """(spec_a, spec_b) for the t5 inter-stage activation pair. ``out``
        selects the stage's last-layer shardings (output constraint /
        cotangent placement), else its first-layer shardings (forward
        transfer into the stage). Zero-layer corners fall back to any valid
        rank-3 spec on the stage."""
        idx = -1 if out else 0
        sh_a = (st.enc_shardings[idx] if st.enc_shardings
                else (st.shardings[idx] if st.shardings else st.vocab))
        sh_b = st.shardings[idx] if st.shardings else sh_a
        return sh_a.act_spec(), sh_b.act_spec()

    def _apply_with_extras(self, st, sp, x, labels=None, loss_mask=None,
                           dropout_rng=None, pos=None, seg=None):
        """Route to the family apply; packed-doc extras are causal-LM only
        (the dataloader and _microbatches both gate t5)."""
        if self.is_t5:
            return self._stage_apply_t5(st, sp, x, labels, loss_mask,
                                        dropout_rng=dropout_rng)
        return self._stage_apply(st, sp, x, labels, loss_mask,
                                 dropout_rng=dropout_rng,
                                 position_ids=pos, segment_ids=seg)

    def _make_fwd(self, st: _Stage) -> Optional[Callable]:
        if st.has_head:
            return None  # head fwd is fused into its value_and_grad backward

        def f(sp, x, rng, pos, seg):
            y, _ = self._apply_with_extras(st, sp, x, dropout_rng=rng,
                                           pos=pos, seg=seg)
            return y
        return jax.jit(f)

    def _make_bwd(self, st: _Stage) -> Callable:
        """(dparams, dx) by recomputing the stage forward (per-stage remat).
        The head stage returns the (unweighted) loss alongside grads so the
        forward never runs separately just for the metric. ``rng`` is the
        same per-(microbatch, stage) key the forward ran with, so the remat
        recomputation reuses the identical dropout masks.

        Under ``hier_dp`` the backward runs vmapped over the dp lane split
        of the microbatch (params unmapped), returning LANE-STACKED
        ``dparams`` with per-lane token-share seeding — the per-device
        contractions are identical to the flat form, only the cross-lane
        summation moves into the post-schedule hierarchical reduce."""
        if self.hier_dp:
            return self._make_bwd_lanes(st)
        if st.has_head:
            def g(sp, x, labels, mask, seed, rng, pos, seg):
                def lf(sp_, x_):
                    return self._apply_with_extras(
                        st, sp_, x_, labels, mask, dropout_rng=rng,
                        pos=pos, seg=seg)
                loss, (dp, dx) = jax.value_and_grad(
                    lambda sp_, x_: lf(sp_, x_), argnums=(0, 1))(sp, x)
                dp = jax.tree.map(lambda t: seed * t, dp)
                dx = jax.tree.map(lambda t: seed * t, dx)
                return dp, dx, loss
            return jax.jit(g)

        def g(sp, x, dy, seed, rng, pos, seg):
            # cotangents: dy for the activation, seed (the microbatch weight)
            # for this stage's MoE aux loss which enters the total directly
            (_, aux), vjp = jax.vjp(
                lambda sp_, x_: self._apply_with_extras(
                    st, sp_, x_, dropout_rng=rng, pos=pos, seg=seg), sp, x)
            dp, dx = vjp((dy, seed))
            return dp, dx, aux
        return jax.jit(g)

    def _make_bwd_lanes(self, st: _Stage) -> Callable:
        """The hier_dp backward variants (see :meth:`_make_bwd`): the
        stage runs with dp-FREE interior constraints (each lane's batch
        slice lives inside one dp group) and the lane axis pinned to the
        dp mesh axes via ``spmd_axis_name`` — without both, the
        partitioner re-shards every lane at every per-layer constraint."""
        from dataclasses import replace as _replace

        from hetu_galvatron_tpu.runtime.trainer import microbatch_weights

        L = max(self.hpc.layers[0].dp_size, 1)
        _nodp = lambda sh: _replace(sh, dp_axes=())
        st = _replace(
            st,
            shardings=[_nodp(s) for s in st.shardings],
            vocab=_nodp(st.vocab) if st.vocab is not None else None,
            enc_shardings=[_nodp(s) for s in (st.enc_shardings or [])])
        spmd_axes = tuple(lower_strategy(self.hpc.layers[0],
                                         st.mesh).dp_axes)

        def vmap_lanes(fn, in_axes):
            return jax.vmap(fn, in_axes=in_axes, spmd_axis_name=spmd_axes)

        def split(a):
            return (None if a is None
                    else a.reshape((L, a.shape[0] // L) + a.shape[1:]))

        def ax(a):
            return None if a is None else 0

        if st.has_head:
            def g(sp, x, labels, mask, seed, rng, pos, seg):
                xl, lbl, mskl = split(x), split(labels), split(mask)
                posl, segl = split(pos), split(seg)
                # per-lane token share: weighted lane masked-means
                # recombine to the flat microbatch mean exactly
                share = microbatch_weights(mskl, L)

                def lane(x_i, lbl_i, msk_i, pos_i, seg_i, w_i):
                    def lf(sp_, x_):
                        return self._apply_with_extras(
                            st, sp_, x_, lbl_i, msk_i, dropout_rng=rng,
                            pos=pos_i, seg=seg_i)
                    loss, (dp, dx) = jax.value_and_grad(
                        lf, argnums=(0, 1))(sp, x_i)
                    dp = jax.tree.map(lambda t: w_i * t, dp)
                    return dp, w_i * dx, loss

                dp_l, dx_l, loss_l = vmap_lanes(
                    lane, (0, 0, ax(mskl), ax(posl), ax(segl), 0))(
                    xl, lbl, mskl, posl, segl, seed * share)
                dx = dx_l.reshape((x.shape[0],) + dx_l.shape[2:])
                return dp_l, dx, jnp.sum(share * loss_l)
            return jax.jit(g)

        def g(sp, x, dy, seed, rng, pos, seg):
            xl, dyl = split(x), split(dy)
            posl, segl = split(pos), split(seg)

            def lane(x_i, dy_i, pos_i, seg_i):
                (_, aux), vjp = jax.vjp(
                    lambda sp_, x_: self._apply_with_extras(
                        st, sp_, x_, dropout_rng=rng, pos=pos_i,
                        seg=seg_i), sp, x_i)
                # aux cotangent seed/L: the flat form seeds the microbatch
                # MEAN aux with `seed`; each lane holds an equal-share mean
                dp, dx = vjp((dy_i, seed / L))
                return dp, dx, aux

            dp_l, dx_l, aux_l = vmap_lanes(
                lane, (0, 0, ax(posl), ax(segl)))(xl, dyl, posl, segl)
            dx = dx_l.reshape((x.shape[0],) + dx_l.shape[2:])
            return dp_l, dx, jnp.mean(aux_l)
        return jax.jit(g)

    def _make_eval(self, st: _Stage) -> Callable:
        """Forward-only stage program with eval semantics (no dropout): the
        head stage returns the held-out loss, others the activation."""
        if st.has_head:
            def f(sp, x, labels, mask, pos, seg):
                return self._apply_with_extras(st, sp, x, labels, mask,
                                               dropout_rng=None,
                                               pos=pos, seg=seg)
            return jax.jit(f)

        def f(sp, x, pos, seg):
            y, _ = self._apply_with_extras(st, sp, x, dropout_rng=None,
                                           pos=pos, seg=seg)
            return y
        return jax.jit(f)

    def eval_step(
        self,
        stage_params: List[Params],
        batch: Dict[str, np.ndarray],
        num_microbatches: Optional[int] = None,
    ) -> Dict[str, float]:
        """Held-out loss under the training plan: forward-only through the
        stage pipeline (reference evaluate() over the valid iterator,
        dataloader.py:462 split machinery). Dropout is off; no optimizer
        state is touched."""
        batch = dict(batch)
        batch.pop("dropout_rng", None)
        if self._eval_jits is None:
            self._eval_jits = [self._make_eval(st) for st in self.stages]
        mbs, weights = self._microbatches(batch, num_microbatches)
        losses = []
        n_stages = len(self.stages)
        for mb in mbs:
            x = self._put_stage0(mb)
            for s in range(n_stages):
                pos, seg = self._put_extras(mb, s)
                if s == n_stages - 1:
                    lbl, msk = self._put_last(mb)
                    losses.append(self._eval_jits[s](
                        stage_params[s], x, lbl, msk, pos, seg))
                else:
                    y = self._eval_jits[s](stage_params[s], x, pos, seg)
                    x = self._transfer(y, s + 1)
        loss = sum(float(w) * float(l) for w, l in zip(weights, losses))
        return {"loss": loss}

    def _make_update(self, st: _Stage) -> Callable:
        tx = self.tx

        def u(sp, opt, grads, scale):
            # expert_bias "gradients" ARE the maintenance update (SGD(1)
            # partition, runtime/optimizer.py) — the global clip must not
            # scale them, matching the SPMD path where clip_by_global_norm
            # lives inside the adam branch only
            grads = jax.tree_util.tree_map_with_path(
                lambda path, g: (g if "expert_bias" in str(path[-1])
                                 else g * scale), grads)
            updates, new_opt = tx.update(grads, opt, sp)
            return optax.apply_updates(sp, updates), new_opt
        return jax.jit(u)

    # ------------------------------------------------------------------
    # schedules
    # ------------------------------------------------------------------

    # batch keys the schedule knows how to place; anything else would be
    # silently dropped by _put_stage0/_put_last/_put_extras, so its presence
    # must be a loud error. position_ids/segment_ids (packed documents,
    # reset_position_ids/reset_attention_mask) are placed on EVERY stage's
    # submesh by the single controller — the reference ships them via
    # multi-tensor p2p instead (pipeline.py:1140 _communicate).
    _SHIPPED_KEYS = frozenset({"tokens", "labels", "loss_mask", "enc_tokens"})
    _EXTRA_KEYS = frozenset({"position_ids", "segment_ids"})

    def _microbatches(self, batch: Dict[str, np.ndarray],
                      num_microbatches: Optional[int] = None):
        shipped = self._SHIPPED_KEYS | (
            frozenset() if self.is_t5 else self._EXTRA_KEYS)
        extra = set(batch) - shipped
        if extra:
            raise NotImplementedError(
                f"the pipeline engine does not thread batch keys "
                f"{sorted(extra)} through its stage transfers")
        m = max(num_microbatches if num_microbatches is not None
                else self.hpc.chunks, 1)
        b = batch["tokens"].shape[0]
        if b % m:
            raise ValueError(f"batch {b} not divisible by chunks {m}")
        mbs = []
        for i in range(m):
            sl = slice(i * (b // m), (i + 1) * (b // m))
            mbs.append({k: np.asarray(v)[sl] for k, v in batch.items()})
        if "loss_mask" in batch:
            counts = np.array([mb["loss_mask"].sum() for mb in mbs],
                              dtype=np.float64)
        else:
            counts = np.ones(m)
        weights = counts / max(counts.sum(), 1.0)
        return mbs, weights

    def _put_stage0(self, mb):
        st = self.stages[0]
        shd = NamedSharding(st.mesh, st.vocab.batch_spec())
        if self.is_t5:
            return (jax.device_put(jnp.asarray(mb["enc_tokens"]), shd),
                    jax.device_put(jnp.asarray(mb["tokens"]), shd))
        return jax.device_put(jnp.asarray(mb["tokens"]), shd)

    def _put_last(self, mb):
        st = self.stages[-1]
        shd = NamedSharding(st.mesh, st.vocab.batch_spec())
        lbl = jax.device_put(jnp.asarray(mb["labels"]), shd)
        msk = (jax.device_put(jnp.asarray(mb["loss_mask"]), shd)
               if "loss_mask" in mb else None)
        return lbl, msk

    def _put_extras(self, mb, s: int):
        """Place packed-doc fields [B, S] on stage s's submesh (every stage
        needs segment_ids for attention masking and position_ids for rope;
        the controller holds the batch, so no inter-stage p2p is needed)."""
        st = self.stages[s]
        spec = (st.shardings[0].batch_spec() if st.shardings
                else st.vocab.batch_spec())
        shd = NamedSharding(st.mesh, spec)
        put = lambda k: (jax.device_put(jnp.asarray(mb[k]), shd)
                         if k in mb else None)
        return put("position_ids"), put("segment_ids")

    def _transfer(self, y, to_stage: int):
        """Move the inter-stage activation (array, or (a, b) pair for t5)
        onto the receiving submesh (ICI DMA on TPU)."""
        st = self.stages[to_stage]
        if self.is_t5:
            spec_a, spec_b = self._carry_specs(st, out=False)
            return jax.device_put(
                y, (NamedSharding(st.mesh, spec_a),
                    NamedSharding(st.mesh, spec_b)))
        spec = (st.shardings[0].act_spec() if st.shardings
                else st.vocab.act_spec())
        return jax.device_put(y, NamedSharding(st.mesh, spec))

    def _put_cotangent(self, dx, to_stage: int):
        """Place the activation cotangent onto the producing stage's submesh
        with that stage's OUTPUT specs."""
        st = self.stages[to_stage]
        if self.is_t5:
            spec_a, spec_b = self._carry_specs(st, out=True)
            return jax.device_put(
                dx, (NamedSharding(st.mesh, spec_a),
                     NamedSharding(st.mesh, spec_b)))
        spec = (st.shardings[-1].act_spec() if st.shardings
                else st.vocab.act_spec())
        return jax.device_put(dx, NamedSharding(st.mesh, spec))

    def _mb_rng(self, ctx, m: int, s: int):
        """Per-(microbatch, stage) dropout key — identical for the forward
        and the backward's remat recomputation of the same microbatch."""
        return jax.random.fold_in(jax.random.fold_in(ctx["rng"], m), s)

    def _fwd_microbatch(self, stage_params, mb, ctx, m):
        """Run one microbatch up to the head stage's input; the head's
        forward happens fused with its backward (value_and_grad), so the
        loss costs no extra pass."""
        x = self._put_stage0(mb)
        inputs = []
        extras = []
        n_stages = len(self.stages)
        for s in range(n_stages):
            inputs.append(x)
            extras.append(self._put_extras(mb, s))
            if s == n_stages - 1:
                lbl, msk = self._put_last(mb)
                ctx["labels"].append((lbl, msk))
                ctx["losses"].append(None)  # filled by the backward
            else:
                pos, seg = extras[s]
                rng = self._mb_rng(ctx, m, s)
                # per-stage XLA flops/bytes (cost/* gauges; the flag is
                # resolved once per step so steady state pays one bool)
                if self._record_costs:
                    maybe_record_jit_cost(f"pp/fwd_s{s}", self._fwd_jits[s],
                                          (stage_params[s], x, rng, pos, seg))
                # host span = dispatch cost; the TraceAnnotation inside
                # carries the stage name into captured XLA device traces
                with span(f"pp/fwd_s{s}"):
                    y = self._fwd_jits[s](stage_params[s], x, rng, pos, seg)
                    x = self._transfer(y, s + 1)
        ctx["inputs"].append(inputs)
        ctx["extras"].append(extras)

    def _bwd_microbatch(self, stage_params, m, w, ctx, grad_acc):
        """Backward for microbatch m seeded with its token weight."""
        inputs = ctx["inputs"][m]
        extras = ctx["extras"][m]
        lbl, msk = ctx["labels"][m]
        seed = jnp.asarray(w, jnp.float32)
        n_stages = len(self.stages)
        pos, seg = extras[-1]
        rng = self._mb_rng(ctx, m, n_stages - 1)
        if self._record_costs:
            maybe_record_jit_cost(
                f"pp/bwd_s{n_stages - 1}", self._bwd_jits[-1],
                (stage_params[-1], inputs[-1], lbl, msk, seed, rng, pos, seg))
        with span(f"pp/bwd_s{n_stages - 1}"):
            dp, dx, loss = self._bwd_jits[-1](
                stage_params[-1], inputs[-1], lbl, msk, seed, rng, pos, seg)
        # keep loss/aux as lazy device scalars — any host sync here would
        # serialize the schedule; train_step folds them once at the end
        aux_parts = []
        grad_acc[-1] = _tree_add(grad_acc[-1], dp)
        for s in range(n_stages - 2, -1, -1):
            dy = self._put_cotangent(dx, s)
            pos, seg = extras[s]
            rng = self._mb_rng(ctx, m, s)
            if self._record_costs:
                maybe_record_jit_cost(
                    f"pp/bwd_s{s}", self._bwd_jits[s],
                    (stage_params[s], inputs[s], dy, seed, rng, pos, seg))
            with span(f"pp/bwd_s{s}"):
                dp, dx, aux = self._bwd_jits[s](
                    stage_params[s], inputs[s], dy, seed, rng, pos, seg)
            if self.cfg.num_experts:
                aux_parts.append(aux)
            grad_acc[s] = _tree_add(grad_acc[s], dp)
        ctx["losses"][m] = loss
        ctx["aux"][m] = aux_parts
        # free stored activations for this microbatch (1F1B memory bound)
        ctx["inputs"][m] = None
        ctx["extras"][m] = None

    def train_step(
        self,
        stage_params: List[Params],
        stage_opts: List[Any],
        batch: Dict[str, np.ndarray],
        num_microbatches: Optional[int] = None,
    ) -> Tuple[List[Params], List[Any], Dict[str, float]]:
        """One optimizer step under the configured schedule.
        ``num_microbatches`` overrides the plan's chunk count (batch-size
        ramp at fixed micro size — the stage jits see the same shapes, so a
        ramp costs zero recompiles here)."""
        batch = dict(batch)
        # per-step dropout key (popped BEFORE microbatch slicing: it is
        # per-step data, not a [B, ...] array). With dropout rates at 0 the
        # key is dead code at trace time, so a constant placeholder is free —
        # but a dropout-ENABLED cfg must get a fresh key per step, else every
        # step reuses identical masks (matching parallel/spmd.py's refusal).
        step_rng = batch.pop("dropout_rng", None)
        if step_rng is None:
            if (self.cfg.hidden_dropout > 0.0
                    or self.cfg.attention_dropout > 0.0):
                raise ValueError(
                    "cfg enables dropout but the batch has no 'dropout_rng' "
                    "key; train_loop/cli add it automatically — manual "
                    "callers must pass one per step")
            step_rng = jax.random.key(0)
        # resolve the one-shot cost/* recording ONCE per step: the inner
        # microbatch loops then pay a single attribute read, never a
        # registry lookup (a sink attached later still records on its
        # first step because the done flag only flips after a live one)
        self._record_costs = (not self._jit_cost_done
                              and bool(get_registry().sinks))
        mbs, weights = self._microbatches(batch, num_microbatches)
        mcount = len(mbs)
        ctx = {"inputs": [], "extras": [], "labels": [], "losses": [],
               "aux": [[] for _ in range(mcount)], "rng": step_rng}
        grad_acc: List[Any] = [None] * len(self.stages)

        if self.hpc.pipeline_type == "gpipe":
            # all forwards, then all backwards (pipeline.py:729-905)
            for m in range(mcount):
                self._fwd_microbatch(stage_params, mbs[m], ctx, m)
            for m in range(mcount):
                self._bwd_microbatch(stage_params, m, weights[m], ctx,
                                     grad_acc)
        else:
            # pipedream-flush / 1F1B (pipeline.py:386-712): warmup forwards,
            # then alternate 1 fwd / 1 bwd, then cooldown backwards. With a
            # single controller the warmup depth is the pipeline depth —
            # in chunks, so interleaved runs keep every group fed.
            warmup = min(len(self.stages), mcount)
            for m in range(warmup):
                self._fwd_microbatch(stage_params, mbs[m], ctx, m)
            next_fwd, next_bwd = warmup, 0
            while next_bwd < mcount:
                self._bwd_microbatch(stage_params, next_bwd,
                                     weights[next_bwd], ctx, grad_acc)
                next_bwd += 1
                if next_fwd < mcount:
                    self._fwd_microbatch(stage_params, mbs[next_fwd], ctx,
                                         next_fwd)
                    next_fwd += 1

        # hierarchical dp reduction (hier_dp): the schedule accumulated
        # LANE-STACKED grads with zero cross-dp bytes; one per-stage
        # three-collective program (rs-intra / ar-cross / ag-intra) sums
        # them HERE, so the tied exchange / global clip / updates below
        # run on ordinary reduced grads, unchanged
        if self.hier_dp:
            with span("pp/hier_reduce"):
                for s in range(len(self.stages)):
                    grad_acc[s] = self._hier_jits[s](grad_acc[s])

        # tied-embedding grad sum across first/last stages (pipeline.py:1042);
        # transposes run jitted on the owning submesh and the sum crosses
        # stages as a device-to-device sharded transfer (ICI on TPU)
        if self.cfg.tie_word_embeddings:
            g_wte = grad_acc[0]["embed"]["wte"]
            g_head = grad_acc[-1]["head"]["whead"]
            g_head_t = jax.device_put(
                self._transpose_jit(g_head),
                NamedSharding(self.stages[0].mesh,
                              self.stages[0].vocab.param_spec(
                                  ("vocab", "embed"))))
            total = g_wte + g_head_t
            grad_acc[0]["embed"]["wte"] = total
            grad_acc[-1]["head"]["whead"] = jax.device_put(
                self._transpose_jit(total),
                NamedSharding(self.stages[-1].mesh,
                              self.stages[-1].vocab.param_spec(
                                  ("embed", "vocab"))))

        # global grad-norm clip across stages — kept ON DEVICE (ADVICE r2):
        # per-stage squared norms fold on stage 0's mesh as replicated
        # scalars, the clip scale is computed there and re-broadcast to each
        # submesh, so no host sync lands between backward and the updates
        rep0 = NamedSharding(self.stages[0].mesh, P())
        sq_parts = [self._gnorm_jit(g) for g in grad_acc]
        total_sq = sq_parts[0]
        for part in sq_parts[1:]:
            total_sq = total_sq + jax.device_put(part, rep0)
        # tied copies are double-counted: subtract one copy
        if self.cfg.tie_word_embeddings:
            total_sq = total_sq - jax.device_put(
                self._gnorm_jit(grad_acc[-1]["head"]["whead"]), rep0)
        gnorm_dev, scale_dev = self._clip_jit(total_sq)

        new_params, new_opts = [], []
        with span("pp/update"):
            for s in range(len(self.stages)):
                scale_s = (scale_dev if s == 0 else jax.device_put(
                    scale_dev, NamedSharding(self.stages[s].mesh, P())))
                p, o = self._update_jits[s](stage_params[s], stage_opts[s],
                                            grad_acc[s], scale_s)
                new_params.append(p)
                new_opts.append(o)
        # single host sync at the very end (all device work already queued)
        loss = sum(float(w) * (float(l) + sum(float(a) for a in aux))
                   for w, l, aux in zip(weights, ctx["losses"], ctx["aux"]))
        if self._record_costs:
            # every per-stage program this step touched is now recorded;
            # later steps skip the registry entirely
            self._jit_cost_done = True
            self._record_costs = False
        return new_params, new_opts, {"loss": loss,
                                      "grad_norm": float(gnorm_dev)}


def _tree_add(a, b):
    if a is None:
        return b
    return jax.tree.map(lambda x, y: x + y, a, b)
