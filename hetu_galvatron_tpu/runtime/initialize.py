"""Framework initialization & global run state.

Capability parity with the reference init layer (runtime/initialize.py:114-246
``initialize_galvatron`` / ``validate_args`` and runtime/parallel_state.py
globals): argument validation, seeding, device/mesh discovery, and the run's
observability writers.

TPU-native: there is no process-group bootstrap — the single-controller JAX
runtime already sees every chip (`jax.devices()`); "initialization" is
validating the plan against the visible world, seeding, and wiring loggers.
The reference's env-based RANK/WORLD_SIZE handshake and NCCL init
(initialize.py:114-160) have no equivalent because XLA owns the transport.
"""

from __future__ import annotations

import logging
import os
import random
from dataclasses import dataclass, field
from typing import Any, List, Optional

import numpy as np

from hetu_galvatron_tpu.core.args_schema import CoreArgs


@dataclass
class RunState:
    """Global run context (the reference's parallel_state globals:
    args/tokenizer/writers/memory buffer, parallel_state.py:135-305)."""

    args: CoreArgs
    devices: List[Any] = field(default_factory=list)
    world_size: int = 1
    logger: Optional[logging.Logger] = None
    tensorboard: Any = None
    wandb: Any = None

    def log(self, msg: str) -> None:
        (self.logger.info if self.logger else print)(msg)


_STATE: Optional[RunState] = None


def get_run_state() -> RunState:
    if _STATE is None:
        raise RuntimeError("initialize() has not been called")
    return _STATE


def validate_args(args: CoreArgs, world_size: int) -> None:
    """Cross-field checks (reference validate_args, initialize.py:190)."""
    m, p = args.model, args.parallel
    if m.hidden_size % m.num_attention_heads:
        raise ValueError("hidden_size must divide by num_attention_heads")
    if m.num_key_value_heads and m.num_attention_heads % m.num_key_value_heads:
        raise ValueError("heads must divide by kv heads")
    if p.config_mode == "global":
        need = p.pp_deg * max(p.global_tp_deg, 1) * max(p.global_cp_deg, 1)
        if world_size % max(need, 1):
            raise ValueError(
                f"world {world_size} not divisible by pp*tp*cp = {need}")
    if m.seq_length > m.max_position_embeddings:
        raise ValueError("seq_length exceeds max_position_embeddings")


def set_seed(seed: int) -> None:
    random.seed(seed)
    np.random.seed(seed)


def _make_logger(args: CoreArgs) -> logging.Logger:
    logger = logging.getLogger("hetu_galvatron_tpu")
    logger.propagate = False  # avoid double lines via the root logger
    if not logger.handlers:
        h = logging.StreamHandler()
        h.setFormatter(logging.Formatter("[%(levelname)s] %(message)s"))
        logger.addHandler(h)
    logger.setLevel(getattr(logging, args.logging.log_level.upper(),
                            logging.INFO))
    return logger


def _make_writers(args: CoreArgs):
    """TensorBoard / wandb writers when configured and importable
    (reference parallel_state.py:85-131; both are optional deps)."""
    tb = wb = None
    if args.logging.tensorboard_dir:
        try:
            from torch.utils.tensorboard import SummaryWriter

            tb = SummaryWriter(args.logging.tensorboard_dir)
        except ImportError:
            pass
    if args.logging.wandb_project:
        try:
            import wandb

            wb = wandb.init(project=args.logging.wandb_project,
                            config=args.model_dump())
        except ImportError:
            pass
    return tb, wb


def initialize_distributed(args: CoreArgs) -> bool:
    """Multi-host runtime init — the TPU-native leg of the reference's
    ``_initialize_distributed`` (runtime/initialize.py:114-160): where the
    reference reads torchrun's RANK/WORLD_SIZE and calls
    ``dist.init_process_group(nccl)``, a TPU pod joins the JAX coordination
    service (``jax.distributed.initialize``), after which ``jax.devices()``
    spans every host's chips and GSPMD collectives ride ICI/DCN.

    Triggered by parallel.num_processes > 1 (explicit) or the
    COORDINATOR_ADDRESS env (launcher-set); on Cloud TPU pods all arguments
    autodetect from the metadata service. Returns True when the
    coordination service was (already) initialized. Safe to call once per
    process; subsequent calls are no-ops.
    """
    import jax

    par = args.parallel
    env_addr = os.environ.get("COORDINATOR_ADDRESS")
    want = par.num_processes > 1 or env_addr is not None
    if not want:
        return False
    if jax.distributed.is_initialized():
        return True
    kwargs = {}
    addr = par.coordinator_address or env_addr
    if addr:
        kwargs["coordinator_address"] = addr
    # env mirrors every config field (NUM_PROCESSES/PROCESS_ID), so a
    # launcher can drive the whole handshake without touching the YAML
    nproc = par.num_processes
    if nproc <= 1 and os.environ.get("NUM_PROCESSES") is not None:
        nproc = int(os.environ["NUM_PROCESSES"])
    if nproc > 1:
        kwargs["num_processes"] = nproc
    pid = par.process_id
    if pid is None and os.environ.get("PROCESS_ID") is not None:
        pid = int(os.environ["PROCESS_ID"])
    if pid is not None:
        kwargs["process_id"] = pid
    jax.distributed.initialize(**kwargs)
    return True


def visible_world_size(args: CoreArgs) -> int:
    """The effective world size a run of ``args`` would see: every
    visible chip, clamped by ``parallel.num_devices`` — the SAME
    derivation :func:`initialize` records in ``RunState.world_size``.
    Joins the coordination service first on multi-host pods (the backend
    must not be probed before ``jax.distributed.initialize``). THE
    helper for every pre-``initialize`` world probe (the elastic resume
    pre-pass, the supervisor's ``world_fn``), so the elastic trigger and
    the actual run state can never disagree about the world."""
    import jax

    initialize_distributed(args)
    world = len(jax.devices())
    if args.parallel.num_devices > 0:
        world = min(args.parallel.num_devices, world)
    return world


def initialize(args: CoreArgs, devices: Optional[List[Any]] = None
               ) -> RunState:
    """Validate + seed + discover devices; returns (and stores) the run
    state (reference initialize_galvatron, initialize.py:142-187 minus the
    process-group/NCCL legs)."""
    global _STATE
    import jax

    if devices is None:
        initialize_distributed(args)
    devices = list(devices if devices is not None else jax.devices())
    world = (args.parallel.num_devices if args.parallel.num_devices > 0
             else len(devices))
    world = min(world, len(devices))
    validate_args(args, world)
    set_seed(args.train.seed)
    logger = _make_logger(args)
    tb, wb = _make_writers(args)
    state = RunState(args=args, devices=devices[:world], world_size=world,
                     logger=logger)
    state.tensorboard, state.wandb = tb, wb
    logger.info("initialized: %d device(s), platform %s, model %s",
                world, devices[0].platform, args.model.model_name)
    _STATE = state
    return state
