"""Device mesh construction + per-layer strategy -> GSPMD sharding lowering.

Capability parity with the reference's comm-group machinery
(runtime/comm_groups.py:266-442 ``gen_comm_groups`` and
runtime/parallel_state.py): where the reference builds NCCL process groups per
layer from the strategy vectors, we lower each :class:`LayerStrategy` to
`PartitionSpec`s over ONE global mesh — XLA materializes the collectives.

TPU-first design — the **binary-factorized mesh**: the per-stage world of
``W = 2^k`` chips becomes ``k`` binary mesh axes ``d0..d{k-1}`` (plus a ``pp``
axis when pp_deg > 1). A layer with (tp=4, dp=2) on W=8 shards its weights
over the two innermost axes ``(d1, d2)`` and its batch over ``d0``; the next
layer with (tp=2, dp=4) uses ``(d2,)`` and ``(d0, d1)``. Because both shardings
live on the same mesh, GSPMD inserts exactly the boundary reshard the
reference implements by hand (split/all-gather "relocation",
runtime/parallel.py:272-304) — heterogeneous per-layer parallelism becomes a
sharding annotation problem instead of a process-group bookkeeping problem.

Axis order follows the reference's rank-coordinate order 'pp-dp-cp-tp'
(comm_groups.py:39-116): tp innermost = adjacent chips = ICI-local, dp
outermost = ready to ride DCN on multi-pod (SURVEY §2.2).

Logical param axes (see models/modules.py init_*) map per layer:
  "qkv"/"mlp"/"heads"  -> the layer's tp axes  (Megatron TP; () under Ulysses)
  "vocab"              -> the vocab layer's vtp axes
  "embed" (2D+ params) -> dp axes under ZeRO-3, else replicated
  anything else        -> replicated
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any, Optional, Sequence, Tuple

import numpy as np

import jax
from jax.sharding import Mesh, PartitionSpec as P

from hetu_galvatron_tpu.utils.strategy import (
    DPType,
    EmbeddingLMHeadStrategy,
    LayerStrategy,
)

# logical param-axis names sharded by tensor parallelism
_TP_LOGICAL = ("qkv", "mlp", "heads", "vocab")

# Canonical sub-axis names for the hierarchical dp/sdp gradient reduction
# (ops/hier_reduce.py): the dp mesh axes are regrouped into an outer
# cross-slice (DCN) sub-axis and an inner intra-host (ICI) sub-axis. These
# names are part of the mesh-axis canon (analysis/lint.py GAL003) — any
# other hand-rolled axis literal in the hierarchical path gets flagged.
HIER_SLICE_AXIS = "slice"  # cross-slice / DCN level (outer dp axes)
HIER_HOST_AXIS = "host"    # intra-host / ICI level (inner dp axes)


def _log2(n: int) -> int:
    k = n.bit_length() - 1
    if n <= 0 or (1 << k) != n:
        raise ValueError(f"{n} is not a positive power of two")
    return k


def dcn_factor_shape(global_shape: Tuple[int, ...], dcn_slices: int
                     ) -> Tuple[int, ...]:
    """Factor ``dcn_slices`` over the LEADING mesh axes (pp first, then the
    outer binary d-axes): pipeline stages and outer-dp replicas cross DCN
    while tp/cp stay on the inner, ICI-local axes — the reference's
    'consecutive ranks on NVLink' locality (comm_groups.py:96-100) lifted to
    the pod level. Returns the per-axis DCN factors; raises when the slices
    cannot divide the leading axes."""
    left = dcn_slices
    out = []
    for dim in global_shape:
        f = math.gcd(left, dim)
        out.append(f)
        left //= f
    if left != 1:
        raise ValueError(
            f"dcn_slices {dcn_slices} does not factor over the leading mesh "
            f"axes {global_shape} (pp * outer-dp must absorb the slices)")
    return tuple(out)


def device_array(
    world_size: int,
    pp_deg: int = 1,
    devices: Optional[Sequence] = None,
    dcn_slices: int = 1,
) -> np.ndarray:
    """Device ndarray of shape ``(pp, 2, ..., 2)`` behind :func:`build_mesh`
    — also used by the pipeline engine to carve DCN-aligned stage groups.

    Order: pp outermost (stage boundaries cross the slowest links), then
    d0..dk with dk fastest-varying (tp-adjacent chips are ICI neighbours,
    the reference's "consecutive" locality, comm_groups.py:96-100).

    ``dcn_slices > 1`` (multi-pod): devices are arranged with
    ``mesh_utils.create_hybrid_device_mesh`` so slice boundaries land on the
    leading axes (pp, then outer d) and every inner axis stays within one
    ICI domain (TPU pods granule by ``slice_index``; multi-process hosts
    without it granule by process). Falls back to the plain enumeration
    order when the devices carry no multi-process topology (tests /
    virtual platforms).
    """
    devices = list(devices if devices is not None else jax.devices())
    if len(devices) < world_size:
        raise ValueError(f"need {world_size} devices, have {len(devices)}")
    devices = devices[:world_size]
    if world_size % pp_deg:
        raise ValueError(f"world {world_size} not divisible by pp {pp_deg}")
    # only the per-stage world must be 2^k (it becomes the binary d-axes);
    # pp is a plain leading axis and may be any size (pp=3 on 24 chips is fine)
    stage = world_size // pp_deg
    k = _log2(stage)
    shape = (pp_deg,) + (2,) * k
    if dcn_slices > 1:
        n_proc = len({getattr(d, "process_index", 0) for d in devices})
        if n_proc > 1:
            from jax.experimental import mesh_utils

            dcn_shape = dcn_factor_shape(shape, dcn_slices)
            ici_shape = tuple(g // f for g, f in zip(shape, dcn_shape))
            # TPU pods carry slice_index; other multi-process platforms
            # (multi-host CPU/GPU) granule by process instead
            by_slice = all(hasattr(d, "slice_index") for d in devices)
            return mesh_utils.create_hybrid_device_mesh(
                ici_shape, dcn_shape, devices=devices,
                process_is_granule=not by_slice)
        # single-process (virtual CPU tests): topology is synthetic anyway;
        # plain enumeration already puts the leading axes outermost
    return np.asarray(devices).reshape(shape)


def build_mesh(
    world_size: int,
    pp_deg: int = 1,
    devices: Optional[Sequence] = None,
    dcn_slices: int = 1,
) -> Mesh:
    """One global mesh: ('pp', 'd0', ..., 'd{k-1}') with binary d-axes over
    the :func:`device_array` arrangement (see there for ordering/DCN)."""
    arr = device_array(world_size, pp_deg, devices, dcn_slices)
    names = ("pp",) + tuple(f"d{i}" for i in range(arr.ndim - 1))
    return Mesh(arr, names)


def stage_axes(mesh: Mesh) -> Tuple[str, ...]:
    """The binary intra-stage axes, outermost first."""
    return tuple(n for n in mesh.axis_names if n != "pp")


def hier_cross_degree(pp_deg: int, dp_deg: int, dcn_slices: int) -> int:
    """How much of a layer's dp degree crosses DCN slice boundaries,
    mirroring :func:`dcn_factor_shape`'s pp-first absorption order: the
    slices land on pp first, the remainder on the outer (dp) mesh axes.
    Returns the cross-slice factor of dp (1 when the job spans one slice);
    raises when the leftover slices cannot divide dp — the same plans
    :func:`dcn_factor_shape` rejects."""
    if dcn_slices <= 1:
        return 1
    left = dcn_slices // math.gcd(dcn_slices, max(pp_deg, 1))
    if max(dp_deg, 1) % left:
        raise ValueError(
            f"dcn_slices {dcn_slices} does not factor over pp {pp_deg} x "
            f"dp {dp_deg} (pp * outer-dp must absorb the slices)")
    return left


def hier_submesh(mesh: Mesh, dp_axes: Sequence[str], cross: int) -> Mesh:
    """Reshaped VIEW of ``mesh`` for the hierarchical dp gradient reduction
    (ops/hier_reduce.py): the (contiguous, leading-stage) ``dp_axes`` are
    regrouped into two axes — :data:`HIER_SLICE_AXIS` of size ``cross``
    (outermost: crosses DCN) and :data:`HIER_HOST_AXIS` of size
    ``dp_deg // cross`` (inner: ICI-local) — while every other axis keeps
    its name and extent. The flat device order is unchanged (adjacent
    binary axes merge), so the view coexists with the global mesh inside
    one jitted program."""
    names = list(mesh.axis_names)
    dp_axes = tuple(dp_axes)
    if not dp_axes:
        raise ValueError("hier_submesh needs at least one dp axis")
    idx = [names.index(a) for a in dp_axes]
    if idx != list(range(idx[0], idx[0] + len(idx))):
        raise ValueError(
            f"dp axes {dp_axes} are not a contiguous run of mesh axes "
            f"{tuple(names)} (non-consecutive tp plans cannot hier-split)")
    dp_deg = axes_size(mesh, dp_axes)
    if cross < 1 or dp_deg % cross:
        raise ValueError(f"cross-slice degree {cross} does not divide the "
                         f"dp degree {dp_deg}")
    lo = idx[0]
    shape = [mesh.shape[n] for n in names]
    new_shape = (tuple(shape[:lo]) + (cross, dp_deg // cross)
                 + tuple(shape[lo + len(dp_axes):]))
    new_names = (tuple(names[:lo]) + (HIER_SLICE_AXIS, HIER_HOST_AXIS)
                 + tuple(names[lo + len(dp_axes):]))
    return Mesh(mesh.devices.reshape(new_shape), new_names)


def axes_size(mesh: Mesh, axes: Sequence[str]) -> int:
    """Product of the named mesh axes' sizes (1 for the empty tuple) — the
    degree a (dp/cp/tp) axis-tuple assignment actually carries. Shared by
    the SPMD lowering and the overlapped-TP dispatch (ops/overlap.py)."""
    n = 1
    for a in axes:
        n *= mesh.shape[a]
    return n


def spec_tree(axes: Any, sh: "LayerSharding", opt: bool = False) -> Any:
    """Map a logical-axis pytree (tuples of axis-name strings at the leaves,
    models/modules.py init_*) to PartitionSpecs under one layer's sharding.
    Shared by the SPMD lowering, the host pipeline engine and the compiled
    pipeline engine."""
    fn = sh.opt_spec if opt else sh.param_spec
    return jax.tree.map(
        fn, axes, is_leaf=lambda x: isinstance(x, tuple)
        and all(isinstance(s, str) for s in x))


def stacked_spec(spec: P) -> P:
    """Spec for a per-stage value stacked along a leading ``[pp, ...]`` axis
    (the compiled pipeline's parameter/activation layout): the stage axis
    rides the mesh's ``pp`` axis, the remaining dims keep their intra-stage
    assignment."""
    return P("pp", *spec)


def make_pp_rotation(mesh: Mesh, spec: P, shift: int):
    """Stage-transfer collective for the compiled pipeline schedule: rotate a
    ``[pp, ...]``-stacked array (sharded :func:`stacked_spec`-style, one
    stage per ``pp`` mesh row) by ``shift`` stages as a `lax.ppermute` over
    the ``pp`` axis — the XLA collective-permute the latency-hiding
    scheduler overlaps with compute, replacing the host engine's
    ``jax.device_put`` submesh transfers. ``spec`` is the FULL stacked spec
    (leading ``pp`` entry included); axes it does not mention are treated as
    replicated (``check_rep=False`` — the rotation is an identity on them).

    ``shift=+1`` sends stage s's slice to stage s+1 (forward activations);
    ``shift=-1`` sends it to stage s-1 (backward cotangents). The wrap-around
    edge carries don't-care data by construction of the 1F1B schedule (lane 0
    embeds fresh tokens; the last lane seeds its cotangent from the loss)."""
    from jax.experimental.shard_map import shard_map

    pp = mesh.shape["pp"]
    perm = [(i, (i + shift) % pp) for i in range(pp)]

    def body(blk):
        # named_scope lands in the HLO metadata so trace attribution can
        # tell stage-rotation permutes from tp-ring / cp-ring permutes when
        # all three coexist in one compiled program
        # (observability/trace_analysis.py)
        with jax.named_scope("pp_rotate"):
            return jax.lax.ppermute(blk, "pp", perm)

    return shard_map(body, mesh, in_specs=spec, out_specs=spec,
                     check_rep=False)


@dataclass(frozen=True)
class LayerSharding:
    """A layer's strategy lowered onto the mesh: which binary axes carry
    dp / cp / tp, plus the dp flavour and remat flag.

    Replaces the reference's per-layer group tuple (tp_group, dp_group,
    cp_group, ... from gen_comm_groups) with named-axis assignments.
    """

    dp_axes: Tuple[str, ...]
    cp_axes: Tuple[str, ...]
    tp_axes: Tuple[str, ...]
    ulysses: bool = False  # tp axes carry sequence (a2a attention), not weights
    dp_type: DPType = DPType.DDP
    checkpoint: bool = False
    # MoE: experts over ep axes (carved from dp), expert weights' mlp axis
    # over etp axes (the reference's pp-ep-edp-etp grid, comm_groups.py:322-345)
    ep_axes: Tuple[str, ...] = ()
    etp_axes: Tuple[str, ...] = ()

    @property
    def edp_axes(self) -> Tuple[str, ...]:
        """Expert-dp: the dp axes not consumed by ep."""
        return self.dp_axes[len(self.ep_axes):]

    # -- param / optimizer-state specs ------------------------------------

    def _weight_axes(self) -> Tuple[str, ...]:
        return () if self.ulysses else self.tp_axes

    @property
    def weight_tp_axes(self) -> Tuple[str, ...]:
        """The mesh axes actually sharding this layer's WEIGHTS — () under
        Ulysses, where the tp axes carry sequence instead. The overlapped-TP
        dispatch keys off this (a layer with no weight-tp axes has no
        collective-vs-matmul pair to decompose)."""
        return self._weight_axes()

    def param_spec(self, logical_axes: Tuple[str, ...],
                   zero3_override: Optional[bool] = None) -> P:
        """PartitionSpec for a param with the given logical axis names.
        Expert params (an "expert" axis present) shard their weight dims over
        etp and their ZeRO-3 embed dim over edp instead of tp/dp."""
        zero3 = (self.dp_type == DPType.ZERO3
                 if zero3_override is None else zero3_override)
        shard_embed = zero3 and len(logical_axes) >= 2
        is_expert = "expert" in logical_axes
        weight_axes = self.etp_axes if is_expert else self._weight_axes()
        embed_axes = self.edp_axes if is_expert else self.dp_axes
        dims = []
        for name in logical_axes:
            if name == "expert":
                dims.append(self.ep_axes or None)
            elif name in _TP_LOGICAL:
                dims.append(weight_axes or None)
            elif name == "embed" and shard_embed:
                dims.append(embed_axes or None)
            else:
                dims.append(None)
        return P(*dims)

    def opt_spec(self, logical_axes: Tuple[str, ...]) -> P:
        """Optimizer-moment spec: ZeRO-2 shards moments over dp even when
        params are replicated (reference SHARD_GRAD_OP, parallel.py:121)."""
        zero3 = self.dp_type in (DPType.ZERO2, DPType.ZERO3)
        return self.param_spec(logical_axes, zero3_override=zero3)

    # -- activation specs --------------------------------------------------

    def act_spec(self) -> P:
        """[B, S, H] hidden-state spec at this layer's boundary:
        batch over dp, sequence over cp (ring) or tp (Megatron-SP/Ulysses),
        hidden replicated."""
        seq = self.cp_axes if self.cp_axes else (self.tp_axes or None)
        return P(self.dp_axes or None, seq or None, None)

    def batch_spec(self) -> P:
        """[B, S] token/label spec."""
        seq = self.cp_axes or None
        return P(self.dp_axes or None, seq)



def lower_strategy(s: LayerStrategy, mesh: Mesh) -> LayerSharding:
    """Assign the mesh's binary axes to (dp, cp, tp) for one layer.

    Consecutive tp (the default) takes the innermost axes; non-consecutive
    tp takes the outermost (the reference's strided groups,
    comm_groups.py:119-203).
    """
    axes = stage_axes(mesh)
    stage = 1 << len(axes)
    need = s.tp_size * s.cp_size * s.dp_size
    if need != stage:
        raise ValueError(
            f"strategy tp{s.tp_size}*cp{s.cp_size}*dp{s.dp_size} = {need} "
            f"!= stage world {stage}")
    ktp, kcp = _log2(s.tp_size), _log2(s.cp_size)
    kdp = _log2(s.dp_size)
    if s.tp_consecutive:
        dp_axes = axes[:kdp]
        cp_axes = axes[kdp:kdp + kcp]
        tp_axes = axes[kdp + kcp:]
    else:
        tp_axes = axes[:ktp]
        cp_axes = axes[ktp:ktp + kcp]
        dp_axes = axes[ktp + kcp:]
    kep, ketp = _log2(s.ep_size), _log2(s.etp_size)
    if kep > len(dp_axes):
        raise ValueError(
            f"ep {s.ep_size} exceeds the dp degree {s.dp_size} it is carved "
            "from (reference grid pp-ep-edp-etp)")
    if ketp > len(tp_axes):
        raise ValueError(f"etp {s.etp_size} exceeds tp {s.tp_size}")
    return LayerSharding(
        dp_axes=dp_axes, cp_axes=cp_axes, tp_axes=tp_axes,
        ulysses=s.sp, dp_type=s.dp_type, checkpoint=s.checkpoint,
        ep_axes=dp_axes[:kep],
        etp_axes=tp_axes[len(tp_axes) - ketp:] if ketp else (),
    )


def lower_vocab_strategy(
    v: EmbeddingLMHeadStrategy, mesh: Mesh, default_dp_type: DPType
) -> LayerSharding:
    """Embedding/LM-head sharding from the vocab strategy (reference
    hp_config_whole_model embedding rows, hybrid_parallel_config.py:276-293):
    tp=vtp (or sequence if vsp), cp=vcp, dp the rest; embed_sdp forces
    ZeRO-3."""
    stage = 1 << len(stage_axes(mesh))
    dp = stage // (v.vtp * v.vcp)
    s = LayerStrategy(
        pp_deg=mesh.shape.get("pp", 1),
        tp_size=v.vtp,
        cp_size=v.vcp,
        dp_size=dp,
        sp=v.vsp,
        dp_type=DPType.ZERO3 if v.embed_sdp else default_dp_type,
    )
    return lower_strategy(s, mesh)
