"""Seeded chaos fault injection for resilience drills.

Generalizes the one-shot ``rerun.inject_kind`` drill into a FAULT PLAN:
a sequence of step-targeted faults — process crashes, preemption
signals, SIGKILL mid-async-save (torn staging dir), committed-meta
corruption/truncation, transient I/O errors through the real
``utils/retrying.py`` seam, and hung saves — driven through the real
training loop and (for process-killing faults) the real cross-process
supervisor, so what a drill certifies is the production recovery path,
not a mock of it.

Fault kinds (``chaos.kind`` or entries of ``chaos.plan``):

* ``crash`` — raise from the step callback: the unhandled-exception
  path (exit 1, flight dump, supervised restart).
* ``sigterm`` — ``kill(self, SIGTERM)`` mid-step: the preemption path
  (PreemptionGuard -> boundary checkpoint -> exit 18).
* ``sigkill`` — abrupt death mid-step, no cleanup: the OOM-killer path
  (negative waitpid code at the supervisor).
* ``kill_mid_save`` — SIGKILL from the checkpoint ``before_commit``
  hook: the payload is fully staged but the COMMITTED marker never
  lands, leaving a torn ``step_<n>.tmp`` the resume must ignore and GC
  must sweep.
* ``hung_save`` — the ``before_commit`` hook sleeps ``chaos.hang_s``:
  exercises the async-checkpoint watchdog (``ckpt.save_timeout_s``).
* ``corrupt_meta`` / ``truncate_meta`` — scribble on / truncate the
  NEWEST committed checkpoint's meta.json: resume must fall back to
  the previous committed step with a warning
  (``load_latest_resilient``), never traceback.
* ``io_error`` — the process-global retry-seam injector
  (``retrying.set_fault_injector``) fails the next
  ``chaos.io_error_count`` attempts of ops matching
  ``chaos.io_error_op``: transient flakiness must be absorbed by
  backoff, not surfaced.

Every fault is ONE-SHOT ACROSS PROCESSES: before firing, a
``CHAOS_FIRED_<i>`` marker lands in ``chaos.state_dir`` (default:
``ckpt.save``), so the relaunched attempt does not re-die at the same
step — exactly how a real transient fault behaves. Unfired faults are
re-armed by the relaunch, so a multi-fault plan unfolds across
attempts. ``chaos.seed`` keys nothing today (faults are step-targeted,
not sampled) but is plumbed so sampled plans stay reproducible.
"""

from __future__ import annotations

import os
import signal
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from hetu_galvatron_tpu.runtime import ckpt_paths
from hetu_galvatron_tpu.utils.retrying import set_fault_injector

FAULT_KINDS = ("crash", "sigterm", "sigkill", "kill_mid_save",
               "hung_save", "corrupt_meta", "truncate_meta", "io_error")

# fault kinds that end the process (the supervisor, not the in-process
# loop, owns recovery): drills asserting on these need mode=process
PROCESS_KILLING = ("sigkill", "kill_mid_save")


class ChaosCrash(RuntimeError):
    """The injected 'unhandled host exception' — a distinct type so
    drill asserts can tell an injected crash from a real bug."""


@dataclass
class Fault:
    kind: str
    at_iter: int = -1           # step the fault arms at (-1 = immediately)
    count: int = 2              # io_error: attempts to fail
    hang_s: float = 5.0         # hung_save: stall length
    op: str = "checkpoint"      # io_error: substring match on the retry op
    index: int = 0              # position in the plan (marker identity)
    fired: bool = False

    def marker(self) -> str:
        return f"CHAOS_FIRED_{self.index}_{self.kind}"


def parse_plan(chaos) -> List[Fault]:
    """Faults from ChaosArgs: ``chaos.plan`` is a comma-separated list of
    ``kind@iter`` entries (``"corrupt_meta@4,crash@5"``); with no plan,
    the single ``chaos.kind``/``chaos.at_iter`` pair (the
    ``rerun.inject_kind`` idiom) is the whole plan."""
    faults: List[Fault] = []
    specs: List[str] = []
    if chaos.plan:
        specs = [s.strip() for s in str(chaos.plan).split(",") if s.strip()]
    elif chaos.kind and chaos.kind != "none":
        specs = [f"{chaos.kind}@{chaos.at_iter}"]
    for i, spec in enumerate(specs):
        kind, _, at = spec.partition("@")
        kind = kind.strip()
        if kind not in FAULT_KINDS:
            raise ValueError(
                f"chaos plan entry {spec!r}: unknown kind {kind!r} "
                f"(one of {', '.join(FAULT_KINDS)})")
        faults.append(Fault(
            kind=kind,
            at_iter=int(at) if at.strip() else -1,
            count=int(chaos.io_error_count),
            hang_s=float(chaos.hang_s),
            op=str(chaos.io_error_op),
            index=i,
        ))
    return faults


class ChaosMonkey:
    """Executes a fault plan against the live training loop.

    Wire-up (``cli/train_dist.py``): construct when ``chaos.enable``;
    ``install()`` before the loop (arms the retry-seam injector),
    ``on_step(it)`` at the top of every step (step-targeted faults),
    ``save_hooks()`` merged into the checkpoint hooks (mid-save
    faults), ``uninstall()`` in the loop's finally.
    """

    def __init__(self, chaos, *, state_dir: Optional[str] = None,
                 registry=None,
                 log: Callable[[str], None] = lambda m: print(m,
                                                              flush=True)):
        self.faults = parse_plan(chaos)
        self.state_dir = state_dir or chaos.state_dir
        self._log = log
        self._registry = registry
        self._iter = -1
        self._prev_injector: Optional[Callable] = None
        self._installed = False
        if self.state_dir:
            os.makedirs(self.state_dir, exist_ok=True)
            for f in self.faults:
                if os.path.exists(os.path.join(self.state_dir, f.marker())):
                    f.fired = True  # already fired in a previous attempt

    # -- bookkeeping --------------------------------------------------------

    def _count(self, kind: str) -> None:
        try:
            reg = self._registry
            if reg is None:
                from hetu_galvatron_tpu.observability.registry import (
                    get_registry,
                )

                reg = get_registry()
            reg.counter("chaos/injected", kind=kind).inc()
        except Exception:  # noqa: BLE001 — chaos telemetry is best-effort
            pass

    def _mark(self, f: Fault) -> None:
        """Persist one-shot-ness BEFORE the fault fires: a SIGKILL'd
        process cannot mark afterwards, and an unmarked fault would
        re-kill every relaunch forever."""
        f.fired = True
        if self.state_dir:
            ckpt_paths.atomic_write_json(
                os.path.join(self.state_dir, f.marker()),
                {"kind": f.kind, "at_iter": f.at_iter, "pid": os.getpid(),
                 "t_wall": time.time()})
        self._count(f.kind)
        self._log(f"chaos: firing {f.kind} (fault #{f.index}, "
                  f"step {self._iter})")

    def pending(self) -> List[str]:
        return [f.kind for f in self.faults if not f.fired]

    # -- the injector seam --------------------------------------------------

    def install(self) -> None:
        if self._installed:
            return
        self._installed = True
        if any(f.kind == "io_error" for f in self.faults):
            self._prev_injector = set_fault_injector(self._io_fault)

    def uninstall(self) -> None:
        if not self._installed:
            return
        self._installed = False
        if any(f.kind == "io_error" for f in self.faults):
            set_fault_injector(self._prev_injector)
            self._prev_injector = None

    def _io_fault(self, op: str) -> Optional[Exception]:
        for f in self.faults:
            if f.fired or f.kind != "io_error":
                continue
            if f.at_iter >= 0 and self._iter < f.at_iter:
                continue
            if f.op and f.op not in op:
                continue
            f.count -= 1
            if f.count <= 0:
                # transient by construction: after `count` failures the
                # op succeeds, so backoff absorbs the fault
                self._mark(f)
            else:
                self._count(f.kind)
                self._log(f"chaos: injecting transient I/O error on "
                          f"{op!r} ({f.count} more)")
            return OSError(f"chaos: injected transient I/O error ({op})")
        return None

    # -- step-targeted faults -----------------------------------------------

    def on_step(self, it: int) -> None:
        """Fire any armed step fault whose ``at_iter`` has arrived.
        Called at the top of the step (before the update), so 'crash at
        step k' loses exactly the steps since the last commit — the RPO
        a drill asserts on."""
        self._iter = it
        for f in self.faults:
            if f.fired or f.at_iter < 0 or it < f.at_iter:
                continue
            if f.kind == "crash":
                self._mark(f)
                raise ChaosCrash(f"chaos: injected crash at step {it}")
            if f.kind == "sigterm":
                self._mark(f)
                os.kill(os.getpid(), signal.SIGTERM)
            elif f.kind == "sigkill":
                self._mark(f)
                os.kill(os.getpid(), signal.SIGKILL)
            elif f.kind in ("corrupt_meta", "truncate_meta"):
                self._corrupt_latest_meta(f)
            # kill_mid_save / hung_save / io_error fire via their seams

    def _corrupt_latest_meta(self, f: Fault) -> None:
        """Scribble on the NEWEST committed checkpoint's meta.json —
        stays armed (unmarked) until a commit exists to corrupt."""
        root = self.state_dir
        latest = ckpt_paths.latest_committed_step(root) if root else None
        if latest is None:
            return
        self._mark(f)
        meta = os.path.join(latest[1], "meta.json")
        if f.kind == "truncate_meta":
            # torn write: half a JSON document
            try:
                with open(meta) as fh:
                    txt = fh.read()
                with open(meta, "w") as fh:
                    fh.write(txt[:max(len(txt) // 2, 1)])
            except OSError:
                pass
        else:
            with open(meta, "w") as fh:
                fh.write("{this is not json")
        self._log(f"chaos: {f.kind} on {meta}")

    # -- mid-save faults ----------------------------------------------------

    def save_hooks(self) -> Dict[str, Callable[..., Any]]:
        """Hooks for the checkpoint seam (``save_checkpoint(hooks=...)``
        / ``AsyncCheckpointer(hooks=...)``): ``before_commit`` runs with
        the payload staged but the COMMITTED marker not yet written —
        the exact window where a death must leave a torn, ignorable
        staging dir."""
        return {"before_commit": self._before_commit}

    def _before_commit(self, tmp_dir: str) -> None:
        step = _step_of_tmp(tmp_dir)
        for f in self.faults:
            if f.fired:
                continue
            if f.at_iter >= 0 and step is not None and step < f.at_iter:
                continue
            if f.kind == "kill_mid_save":
                self._mark(f)
                os.kill(os.getpid(), signal.SIGKILL)
            elif f.kind == "hung_save":
                self._mark(f)
                self._log(f"chaos: hanging save of {tmp_dir} for "
                          f"{f.hang_s:.1f}s")
                time.sleep(f.hang_s)


def _step_of_tmp(tmp_dir: str) -> Optional[int]:
    name = os.path.basename(tmp_dir.rstrip("/"))
    if name.endswith(ckpt_paths.TMP_SUFFIX):
        name = name[: -len(ckpt_paths.TMP_SUFFIX)]
    return ckpt_paths.step_of(name)


def make_chaos(args, *, registry=None,
               log: Callable[[str], None] = lambda m: print(m, flush=True)
               ) -> Optional[ChaosMonkey]:
    """The train_dist construction seam: None unless ``chaos.enable``."""
    chaos = getattr(args, "chaos", None)
    if chaos is None or not chaos.enable:
        return None
    state_dir = chaos.state_dir or args.ckpt.save or None
    monkey = ChaosMonkey(chaos, state_dir=state_dir, registry=registry,
                         log=log)
    if not monkey.faults:
        return None
    return monkey
