"""Compiled pipeline schedule: the whole 1F1B step as ONE SPMD program.

The host engine (:mod:`runtime.pipeline`) sequences its schedule from the
host — one jitted call per (stage, microbatch) leg, ~375 us of dispatch each
(PERF.md round 5) and no *guaranteed* device overlap. This module is the
idiomatic XLA answer to VERDICT r4 weak #5: compile the ENTIRE 1F1B schedule
(warmup forwards, steady-state one-forward-one-backward, cooldown backwards,
gradient accumulation, tied-embedding grad exchange, global-norm clip and the
optimizer update) into a single GSPMD program over a full ``(pp, d0..dk)``
mesh, so XLA's latency-hiding scheduler overlaps the inter-stage transfers
with compute ("The Big Send-off", PAPERS.md).

Layout and mechanics:

* **One mesh, real pp axis** — ``build_mesh(world, pp)`` instead of the host
  engine's disjoint per-stage submeshes. Per-stage decoder weights are
  STACKED along a leading ``[pp, ...]`` axis sharded on the ``pp`` mesh axis
  (``mesh.stacked_spec``), so stage s's slice physically lives on mesh row s.
  The vocab layers (embed / prenorm / head) are replicated across ``pp``
  rows; replication + psum-through-autodiff is what fuses the tied-embedding
  grad exchange into the program (see below).
* **Lockstep tick scan** — a `lax.scan` over ``T = m + 2(pp-1)`` schedule
  ticks (m microbatches). At tick t, stage s runs the FORWARD of microbatch
  ``i = t - s`` (when ``0 <= i < m``) and the BACKWARD of microbatch
  ``j = t - 2(pp-1) + s``; both units execute as ONE stacked computation
  over the leading stage axis, which GSPMD partitions along ``pp`` — every
  mesh row computes only its own stage. Bubble ticks are masked by zeroing
  the backward cotangent seeds (zero cotangent in => exactly-zero grads out,
  by linearity of the vjp) and by `where`-gating the loss/grad accumulators.
* **De-vmapped stage axis (shard_map kernels inside)** — the per-stage
  layer computation is NOT a vmap over stage lanes (it was, through round
  11): stage-stacked weights enter ordinary traced einsums with an explicit
  leading ``p`` batch dim (``"pbsh,phf->pbsf"``), weight-free segments
  (norms, rope, residuals, the XLA attention core, per-lane dropout keys)
  ride plain `jax.vmap` over the lane axis, and the shard_map kernels —
  ``ops/overlap.py`` ring ag/rs matmuls (``tp_overlap=True``), the Pallas
  flash kernel, Ulysses a2a and cp/zigzag ring attention — are built with
  ``stage_axis="pp"``: ONE full-manual shard_map spanning the whole mesh
  whose specs carry the stage lane, exactly like the ``ppermute`` stage
  rotations always did. No nesting, no vmapped shard_map — the two flagship
  perf features (single-program 1F1B + overlapped/kernel collectives)
  compose in one donated jit. (Partial-auto shard_map — manual over ``pp``
  only — hard-crashes the XLA partitioner on this jax pin; the stacked
  full-manual form is the shape that works.)
* **collective-permute stage transfers** — activations rotate ``s -> s+1``
  and cotangents ``s -> s-1`` with `lax.ppermute` over the ``pp`` axis
  (``mesh.make_pp_rotation``), the compiled analogue of the reference's
  batched isend/irecv and of the host engine's `jax.device_put` hops.
* **1F1B memory bound** — the backward recomputes its stage forward from the
  stored stage INPUT (`jax.vjp`, per-stage remat — same policy as the host
  engine), so each stage keeps a circular buffer of ``2*pp - 1`` in-flight
  stage inputs: O(pp), independent of the microbatch count (GPipe would be
  O(m)). The depth-``2pp-1`` bound (vs the host schedule's ``pp``) is the
  price of the lockstep fwd+bwd tick; slot reuse is provably collision-free
  because a slot distance of a full buffer length can never separate two
  live microbatches of one stage.
* **Tied embeddings for free** — the last stage's logits use ``wte.T``
  directly (the table is replicated across ``pp``), so autodiff SUMS the
  embedding-lookup grad (stage 0's lane) and the head grad (last lane) into
  one ``wte`` cotangent — the host engine's explicit transpose-and-exchange
  becomes a psum the partitioner places.
* **Redundant vocab compute** — under the vmapped lockstep tick every mesh
  row also executes the (masked) head matmul in backward ticks; only the
  last row's result carries a non-zero cotangent. This trades ~one
  layer-equivalent of per-tick compute for a schedule with zero host
  dispatch; the embedding lookup itself is batched OUT of the vmap (its
  inputs are lane-invariant) and costs nothing extra.

Eligibility (everything else falls back to the host engine, which stays the
general path): causal-LM / bert families (no t5 pair carry), vpp=1, uniform
``pp_division`` and a uniform per-layer strategy (stacking needs one shard
layout), no MoE, no packed-document fields. Context parallelism (plain and
zigzag), Megatron-SP tp with the overlapped ring matmuls, Ulysses, and the
Pallas flash kernel all run INSIDE the program via the stage-stacked
shard_map wrappers; `tools/pipeline_dispatch_bench.py --kernels` and
`tools/tp_overlap_bench.py --schedule-impl compiled` measure the composition.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from hetu_galvatron_tpu.core.args_schema import ModelArgs, TrainArgs
from hetu_galvatron_tpu.models import modules as M
from hetu_galvatron_tpu.observability.registry import get_registry
from hetu_galvatron_tpu.observability.trace_analysis import (
    maybe_record_jit_cost,
)
from hetu_galvatron_tpu.observability.tracing import span
from hetu_galvatron_tpu.runtime.hybrid_config import HybridParallelConfig
from hetu_galvatron_tpu.runtime.mesh import (
    axes_size,
    build_mesh,
    lower_strategy,
    lower_vocab_strategy,
    make_pp_rotation,
    spec_tree,
    stacked_spec,
)
from hetu_galvatron_tpu.runtime.trainer import microbatch_weights

Params = Dict[str, Any]


def _stacked_decay_mask(params: Params) -> Params:
    """Weight-decay mask for the stacked layout: the plain rule is
    ``ndim >= 2`` (runtime/optimizer.py `_decay_mask`), but ``stages`` leaves
    carry a leading ``[pp]`` stage axis that must not promote a stacked bias
    into a decayed "matrix"."""
    return {
        k: jax.tree.map(
            lambda p, off=(1 if k == "stages" else 0): p.ndim - off >= 2, v)
        for k, v in params.items()
    }


def _compiled_optimizer(train: TrainArgs) -> optax.GradientTransformation:
    """Host-parity optimizer (pipeline._pipeline_optimizer: Adam + wd +
    schedule WITHOUT the global clip — the clip scale is applied explicitly
    so it is global across stages) with the stacking-aware decay mask."""
    from hetu_galvatron_tpu.runtime.optimizer import (
        make_lr_schedule,
        partition_expert_bias,
    )

    chain = [optax.scale_by_adam(b1=train.adam_beta1, b2=train.adam_beta2,
                                 eps=train.adam_eps)]
    if train.weight_decay:
        chain.append(optax.add_decayed_weights(train.weight_decay,
                                               mask=_stacked_decay_mask))
    chain.append(optax.scale_by_learning_rate(make_lr_schedule(train)))
    return partition_expert_bias(optax.chain(*chain))


class CompiledPipelineEngine:
    """Single-program 1F1B: same external contract as ``PipelineEngine``
    (split_params / init_opt / train_step / eval_step / merge_params), but
    params are one pp-stacked tree instead of a list of per-stage trees and
    the whole optimizer step is one donated jit call."""

    @staticmethod
    def unsupported_reason(cfg: ModelArgs, hpc: HybridParallelConfig,
                           data: Any = None) -> Optional[str]:
        """None when the compiled schedule can express this plan; otherwise
        a human-readable reason the launcher logs before falling back to the
        host engine. The predicate itself lives in
        ``analysis/eligibility.py`` — shared with the cost model's
        dispatch-waiver gate and the plan doctor, so the three can never
        drift. (cp / zigzag-cp plans are expressible since the stage axis
        was de-vmapped: the ring-attention kernel runs inside the program
        as a stage-stacked full-manual shard_map, like the overlapped-TP
        ring matmuls and the flash kernel.)"""
        from hetu_galvatron_tpu.analysis.eligibility import (
            compiled_unsupported_reason,
        )

        return compiled_unsupported_reason(cfg, hpc, data)

    def __init__(
        self,
        cfg: ModelArgs,
        hpc: HybridParallelConfig,
        train: TrainArgs,
        devices: Optional[List] = None,
        *,
        compute_dtype=jnp.bfloat16,
        dcn_slices: int = 1,
        donate: bool = True,
        tp_overlap: bool = False,
        use_flash: Optional[bool] = None,
        flash_interpret: bool = False,
        hier_dp: bool = False,
        hier_bucket_mb: float = 0.0,
    ):
        """``tp_overlap`` swaps the (uniform) layer's projection matmuls for
        the stage-stacked ring ag/rs kernels (ops/overlap.py) when the layer
        is eligible; ``self.overlap_reason`` carries the reason otherwise.
        ``use_flash`` mirrors the host engine's attention dispatch: None =
        the platform default (Pallas flash on TPU when cfg.use_flash_attn),
        an explicit bool forces it; ``flash_interpret`` runs the Pallas
        kernels in interpret mode (CPU parity drills). ``hier_dp`` runs
        the backward units per dp LANE (a vmap over the lane-split batch
        slices of the per-tick vjp) with lane-local grad accumulation
        through the tick scan, and reduces ONCE after it via the explicit
        hierarchical reduce-scatter/all-reduce/all-gather program
        (ops/hier_reduce.py) — the dp traffic leaves the scan and the
        cross-slice hop carries only the 1/intra shard. Ineligible plans
        (eligibility.hier_dp_unsupported_reason; any shard_map kernel —
        rings/flash/cp/ulysses — cannot nest under the lane vmap) raise,
        mirroring the unsupported-plan ctor contract."""
        reason = self.unsupported_reason(cfg, hpc)
        if reason is not None:
            raise ValueError(f"compiled pipeline schedule unsupported: "
                             f"{reason}")
        self.cfg = cfg
        self.hpc = hpc
        self.train = train
        self.compute_dtype = compute_dtype
        self.donate = donate
        self.pp = hpc.pp_deg
        self.lps = hpc.pp_division[0]  # layers per stage (uniform)
        devices = list(devices if devices is not None else jax.devices())
        if len(devices) < hpc.world_size:
            raise ValueError(
                f"need {hpc.world_size} devices, have {len(devices)}")
        self.mesh = build_mesh(hpc.world_size, self.pp,
                               devices=devices[:hpc.world_size],
                               dcn_slices=dcn_slices)
        self.layer_sh = lower_strategy(hpc.layers[0], self.mesh)
        self.vocab_sh = lower_vocab_strategy(hpc.vocab, self.mesh,
                                             hpc.default_dp_type)
        self.tx = _compiled_optimizer(train)
        self._use_dropout = (cfg.hidden_dropout > 0.0
                             or cfg.attention_dropout > 0.0)
        self._use_flash = use_flash
        self._sdpa = self._build_attention_core(flash_interpret)
        # overlapped-TP ring matmuls inside the program (the same per-layer
        # eligibility the SPMD/host paths apply; the plan is uniform, so one
        # decision covers every decoder layer)
        self.tp_overlap = False
        self.overlap_reason: Optional[str] = None
        self._matmul_fns: Dict[str, Any] = {}
        if tp_overlap:
            from hetu_galvatron_tpu.ops.overlap import (
                layer_overlap_reason,
                make_layer_matmuls,
            )

            tp_axes = self.layer_sh.weight_tp_axes
            reason = layer_overlap_reason(
                cfg, self.layer_sh, axes_size(self.mesh, tp_axes))
            if reason is None:
                self._matmul_fns = make_layer_matmuls(
                    self.mesh, self.layer_sh.dp_axes, tp_axes,
                    stage_axis="pp")
                self.tp_overlap = True
            else:
                self.overlap_reason = reason
        # hierarchical dp gradient reduction (ops/hier_reduce.py): validate
        # eligibility here (ctor contract); the reducer itself binds to the
        # grad specs, which need the axes tree — built in split_params
        self.hier_dp = bool(hier_dp)
        self._dcn_slices = dcn_slices
        self._hier_bucket_mb = float(hier_bucket_mb)
        self._hier = None
        if self.hier_dp:
            from hetu_galvatron_tpu.analysis.eligibility import (
                HIER_KERNEL_REASON,
                plan_hier_dp_reason,
            )

            reason = plan_hier_dp_reason(cfg, hpc)
            if reason is None and (self._matmul_fns or
                                   self._sdpa is not None):
                reason = HIER_KERNEL_REASON
            if reason is not None:
                raise ValueError(f"hier_dp unsupported: {reason}")
        # jit caches keyed by microbatch count (a batch-size ramp compiles
        # one program per distinct count; a fixed plan compiles exactly once)
        self._step_jits: Dict[int, Any] = {}
        self._eval_jits: Dict[int, Any] = {}

    def _build_attention_core(self, flash_interpret: bool):
        """The stage-stacked attention core for the (uniform) layer
        strategy — mirrors ``parallel/spmd.attention_overrides``: cp layers
        get ring attention over their cp axes, Ulysses layers the
        head-scatter a2a sandwich, flash-eligible layers the Pallas kernel;
        None means the vmapped XLA core (GSPMD inserts the collectives).
        Every kernel is built with ``stage_axis='pp'`` so it runs on the
        ``[pp, ...]``-stacked activations as one full-manual shard_map."""
        sh = self.layer_sh
        cfg = self.cfg
        use_flash = self._use_flash
        if use_flash is None:
            use_flash = bool(cfg.use_flash_attn) and all(
                d.platform == "tpu" for d in self.mesh.devices.flat[:1])
        if sh.cp_axes:
            from hetu_galvatron_tpu.ops.ring_attention import make_ring_sdpa

            zig = bool(getattr(self.hpc, "cp_zigzag", False))
            return make_ring_sdpa(
                self.mesh, sh.cp_axes, dp_axes=sh.dp_axes,
                tp_axes=sh.tp_axes, use_flash=use_flash, zigzag=zig,
                data_zigzagged=zig, interpret=flash_interpret,
                stage_axis="pp")
        if sh.ulysses and sh.tp_axes:
            from hetu_galvatron_tpu.ops.ulysses import make_ulysses_sdpa

            local = None
            if use_flash:
                from hetu_galvatron_tpu.ops.pallas.flash_attention import (
                    flash_sdpa,
                )

                local = (partial(flash_sdpa, interpret=True)
                         if flash_interpret else flash_sdpa)
            return make_ulysses_sdpa(self.mesh, sh.tp_axes,
                                     dp_axes=sh.dp_axes, local_sdpa=local,
                                     stage_axis="pp")
        if use_flash:
            from hetu_galvatron_tpu.ops.pallas.flash_attention import (
                make_flash_sdpa,
            )

            return make_flash_sdpa(self.mesh, dp_axes=sh.dp_axes,
                                   tp_axes=sh.tp_axes,
                                   interpret=flash_interpret,
                                   stage_axis="pp")
        return None

    # ------------------------------------------------------------------
    # params / optimizer state (stacked layout)
    # ------------------------------------------------------------------

    def _slot_axes(self, axes: Params, j: int) -> Params:
        """Logical-axis tree for stage-layer slot j (identical across
        stages under the uniform-strategy gate)."""
        return axes["layers"][j]

    def stacked_param_specs(self, axes: Params, opt: bool = False) -> Params:
        """PartitionSpec tree mirroring the stacked params: ``stages`` slot
        leaves get P('pp', *layer_spec); vocab-row leaves (embed / prenorm /
        head) keep the vocab sharding and replicate across pp."""
        isP = lambda x: isinstance(x, P)
        out: Params = {"stages": tuple(
            jax.tree.map(stacked_spec,
                         spec_tree(self._slot_axes(axes, j), self.layer_sh,
                                   opt),
                         is_leaf=isP)
            for j in range(self.lps))}
        for k in ("embed", "prenorm", "head"):
            out[k] = spec_tree(axes[k], self.vocab_sh, opt)
        return out

    def _nshd(self, spec_tree_: Any) -> Any:
        return jax.tree.map(lambda s: NamedSharding(self.mesh, s),
                            spec_tree_, is_leaf=lambda x: isinstance(x, P))

    def _stacked_grad_specs(self, axes: Params) -> Params:
        """Grad-layout spec tree for the hierarchical reducer: the stacked
        param specs with ZeRO-3 dp-sharding overridden OFF (the reduction's
        lane axis owns the dp mesh axes — ops/hier_reduce.py)."""
        isP = lambda x: isinstance(x, P)
        is_axes = lambda x: (isinstance(x, tuple)
                             and all(isinstance(s, str) for s in x))
        tree = lambda a, sh: jax.tree.map(
            lambda la: sh.param_spec(la, zero3_override=False), a,
            is_leaf=is_axes)
        out: Params = {"stages": tuple(
            jax.tree.map(stacked_spec,
                         tree(self._slot_axes(axes, j), self.layer_sh),
                         is_leaf=isP)
            for j in range(self.lps))}
        for k in ("embed", "prenorm", "head"):
            out[k] = tree(axes[k], self.vocab_sh)
        return out

    def _build_hier(self, axes: Params) -> None:
        from hetu_galvatron_tpu.ops.hier_reduce import HierDpReducer
        from hetu_galvatron_tpu.runtime.mesh import hier_cross_degree

        dp_axes = self.layer_sh.dp_axes
        dp_deg = axes_size(self.mesh, dp_axes)
        cross = hier_cross_degree(self.pp, dp_deg, self._dcn_slices)
        self._hier = HierDpReducer(
            mesh=self.mesh, dp_axes=dp_axes, cross=cross,
            intra=dp_deg // cross, specs=self._stacked_grad_specs(axes),
            bucket_mb=self._hier_bucket_mb)

    def split_params(self, params: Params, axes: Params) -> Params:
        """Full (host/single-device) params tree -> the stacked layout:
        decoder layer ``s*lps + j`` becomes row s of ``stages[j]``; the
        vocab rows are placed replicated across pp. The tied head carries NO
        transposed copy — the program reads ``wte.T`` directly."""
        n = self.pp * self.lps
        if len(params["layers"]) != n:
            raise ValueError(f"params have {len(params['layers'])} layers, "
                             f"plan has {n}")
        stages = tuple(
            jax.tree.map(lambda *leaves: jnp.stack(leaves),
                         *[params["layers"][s * self.lps + j]
                           for s in range(self.pp)])
            for j in range(self.lps))
        sp: Params = {"stages": stages, "embed": params["embed"],
                      "prenorm": params["prenorm"], "head": params["head"]}
        # remember the embed's logical axes so the step program can state
        # the ZeRO-3 use-site gather explicitly (spmd
        # make_embed_use_constraint); without it the program is still
        # correct, just chattier to partition
        self._embed_axes = axes["embed"]
        if self.hier_dp and self._hier is None:
            self._build_hier(axes)
        specs = self.stacked_param_specs(axes)
        self._param_shardings = self._nshd(specs)
        # stage through a host copy: device_put of a fully-replicated leaf
        # can ALIAS the caller's buffer, and the donated step would then
        # delete the caller's params out from under it
        return jax.tree.map(
            lambda p, s: jax.device_put(np.asarray(p),
                                        NamedSharding(self.mesh, s)),
            sp, specs)

    def merge_params(self, sp: Params) -> Params:
        """Stacked layout -> the full host tree (tests / checkpointing),
        matching ``PipelineEngine.merge_params`` output structure."""
        stages = jax.device_get(sp["stages"])
        layers: List[Params] = []
        for s in range(self.pp):
            for j in range(self.lps):
                layers.append(jax.tree.map(lambda x: np.asarray(x)[s],
                                           stages[j]))
        return {"layers": tuple(layers),
                "embed": jax.device_get(sp["embed"]),
                "prenorm": jax.device_get(sp["prenorm"]),
                "head": jax.device_get(sp["head"])}

    def init_opt(self, sp: Params, axes: Params) -> Any:
        from hetu_galvatron_tpu.parallel.spmd import opt_state_specs

        opt_pspecs = self.stacked_param_specs(axes, opt=True)
        specs = opt_state_specs(self.tx, sp, opt_pspecs)
        self._opt_shardings = self._nshd(specs)
        init = jax.jit(self.tx.init, out_shardings=self._opt_shardings)
        return init(sp)

    # ------------------------------------------------------------------
    # stacked stage programs (explicit leading [pp] stage axis — NOT a
    # vmap, so the shard_map kernels run inside; weight-free segments ride
    # plain vmaps over the lane axis, which trace identically to the old
    # per-lane form)
    # ------------------------------------------------------------------

    def _lane_keys(self, step_rng, mbs):
        """[pp] per-(microbatch, stage) dropout keys — same derivation as
        the host engine's ``_mb_rng`` (and the old vmapped core), so a
        compiled run replays identical masks. None when dropout is off."""
        if step_rng is None or not self._use_dropout:
            return None
        lanes = jnp.arange(self.pp)
        return jax.vmap(lambda mb, lane: jax.random.fold_in(
            jax.random.fold_in(step_rng, mb), lane))(mbs, lanes)

    def _st_dropout(self, x, rate, rngs):
        """Per-lane inverted dropout on a ``[pp, ...]`` stacked value:
        vmapped over the lane keys, bit-identical to the host engine's
        per-stage masks under the partitionable threefry rng."""
        if rngs is None or rate <= 0.0:
            return x
        return jax.vmap(lambda xl, r: M.dropout(xl, rate, r))(x, rngs)

    def _st_norm(self, p, x):
        """Stacked per-layer norm: params carry the leading ``[pp]`` stage
        axis; per-lane apply_norm under vmap keeps the fp32 arithmetic
        bit-identical to the host engine's per-stage call."""
        if not p:
            return x
        return jax.vmap(lambda pl, xl: M.apply_norm(pl, xl, self.cfg))(p, x)

    def _stacked_attention(self, p, x, rope, attn_rngs, causal):
        """modules.apply_attention on a ``[pp, B, S, H]`` stacked stream
        with ``[pp, ...]`` stacked weights: the projections run as explicit
        leading-axis einsums — or the stage-stacked ring kernels when
        ``tp_overlap`` is on — and the attention core is the stage-stacked
        kernel from ``_build_attention_core`` (vmapped XLA core when None).
        Mirrors the module's dtype casts and dropout dispatch rules."""
        cfg = self.cfg
        cd = self.compute_dtype
        mm = self._matmul_fns
        pp_, B, S, _ = x.shape
        hd = cfg.head_dim
        nq, nkv = cfg.num_attention_heads, cfg.kv_heads
        w = p["wqkv"].astype(cd)
        if "qkv" in mm:
            qkv = mm["qkv"](x.astype(cd), w)
        else:
            qkv = jnp.einsum("pbsh,phf->pbsf", x.astype(cd), w,
                             preferred_element_type=jnp.float32)
        if "bqkv" in p:
            qkv = qkv + p["bqkv"][:, None, None, :]
        qkv = qkv.astype(cd)
        q, k, v = jnp.split(qkv, [nq * hd, (nq + nkv) * hd], axis=-1)
        q = q.reshape(pp_, B, S, nq, hd)
        k = k.reshape(pp_, B, S, nkv, hd)
        v = v.reshape(pp_, B, S, nkv, hd)
        if rope is not None:
            cos, sin = rope
            q = M.apply_rope(q, cos, sin)
            k = M.apply_rope(k, cos, sin)
        core = self._sdpa
        use_drop = attn_rngs is not None and cfg.attention_dropout > 0.0
        if use_drop:
            if core is None:
                out = jax.vmap(lambda qq, kk, vv, rr: M.xla_sdpa(
                    qq, kk, vv, causal=causal,
                    dropout_rate=cfg.attention_dropout,
                    dropout_rng=rr))(q, k, v, attn_rngs)
            elif getattr(core, "supports_dropout", False):
                out = core(q, k, v, causal=causal,
                           dropout_rate=cfg.attention_dropout,
                           dropout_rng=attn_rngs)
            else:
                # same refusal as modules.apply_attention: silently swapping
                # a ring/Ulysses kernel for the score-materializing XLA core
                # would be an OOM/perf cliff on the plans it exists for
                raise NotImplementedError(
                    "attention_dropout > 0 is only supported with the XLA "
                    "attention core and the Pallas flash kernel; the "
                    "installed ring/Ulysses kernel has no dropout variant. "
                    "Avoid cp/ulysses layers or set "
                    "model.attention_dropout=0; hidden_dropout works with "
                    "every kernel")
        elif core is None:
            out = jax.vmap(lambda qq, kk, vv: M.xla_sdpa(
                qq, kk, vv, causal=causal))(q, k, v)
        else:
            out = core(q, k, v, causal=causal)
        out = out.reshape(pp_, B, S, nq * hd)
        wo = p["wo"].astype(cd)
        if "out" in mm:
            y = mm["out"](out, wo)
        else:
            y = jnp.einsum("pbsf,pfh->pbsh", out, wo,
                           preferred_element_type=jnp.float32)
        if "bo" in p:
            y = y + p["bo"][:, None, None, :]
        return y.astype(cd)

    def _stacked_mlp(self, p, x):
        """modules.apply_mlp with stacked weights (gated/plain, bias adds,
        and the fc1_pair overlapped form all mirrored)."""
        cfg = self.cfg
        cd = self.compute_dtype
        mm = self._matmul_fns
        act = M._ACTS[cfg.hidden_act]
        win = p["win"].astype(cd)
        gated = cfg.hidden_act in ("swiglu", "geglu")
        if gated and "fc1_pair" in mm:
            F = p["wout"].shape[1]
            gate, up = mm["fc1_pair"](x.astype(cd), win[..., :F],
                                      win[..., F:])
            if "bin" in p:
                gate = gate + p["bin"][:, None, None, :F]
                up = up + p["bin"][:, None, None, F:]
            hproj = act(gate.astype(cd)) * up.astype(cd)
        else:
            if "fc1" in mm:
                hproj = mm["fc1"](x.astype(cd), win)
            else:
                hproj = jnp.einsum("pbsh,phf->pbsf", x.astype(cd), win,
                                   preferred_element_type=jnp.float32)
            if "bin" in p:
                hproj = hproj + p["bin"][:, None, None, :]
            hproj = hproj.astype(cd)
            if gated:
                gate, up = jnp.split(hproj, 2, axis=-1)
                hproj = act(gate) * up
            else:
                hproj = act(hproj)
        wout = p["wout"].astype(cd)
        if "fc2" in mm:
            y = mm["fc2"](hproj, wout)
        else:
            y = jnp.einsum("pbsf,pfh->pbsh", hproj, wout,
                           preferred_element_type=jnp.float32)
        if "bout" in p:
            y = y + p["bout"][:, None, None, :]
        return y.astype(cd)

    def _stacked_decoder_layer(self, p, x, rope, layer_keys, causal):
        """modules.apply_decoder_layer on the stacked stream: pre-norm or
        post-norm (bert) residual block with per-lane dropout keys split
        exactly like the module does."""
        cfg = self.cfg
        r_attn = r_res1 = r_res2 = None
        if layer_keys is not None:
            r3 = jax.vmap(lambda kk: jax.random.split(kk, 3))(layer_keys)
            r_attn, r_res1, r_res2 = r3[:, 0], r3[:, 1], r3[:, 2]
        drop = lambda y, rr: self._st_dropout(y, cfg.hidden_dropout, rr)
        if cfg.post_norm:
            x = self._st_norm(
                p["ln1"],
                x + drop(self._stacked_attention(p["attn"], x, rope, r_attn,
                                                 causal), r_res1))
            return self._st_norm(
                p["ln2"],
                x + drop(self._stacked_mlp(p["mlp"], x), r_res2))
        h = self._st_norm(p["ln1"], x)
        x = x + drop(self._stacked_attention(p["attn"], h, rope, r_attn,
                                             causal), r_res1)
        h = self._st_norm(p["ln2"], x)
        x = x + drop(self._stacked_mlp(p["mlp"], h), r_res2)
        return x

    def _stacked_layers(self, stages_w, x, lane_keys):
        """The Lps decoder-layer slots on the stacked stream (per-layer
        remat honored, same checkpoint policy as the host engine)."""
        cfg = self.cfg
        rope = None
        if cfg.position_embedding_type == "rope":
            cos, sin = M.rope_cos_sin(x.shape[2], cfg.head_dim,
                                      cfg.rope_theta,
                                      scaling=cfg.rope_scaling)
            rope = (cos, sin)
        causal = cfg.model_type != "bert"
        for j, lp in enumerate(stages_w):
            keys = None
            if lane_keys is not None:
                keys = jax.vmap(
                    lambda kk, _j=j: jax.random.fold_in(kk, _j))(lane_keys)
            fn = partial(self._stacked_decoder_layer, rope=rope,
                         layer_keys=keys, causal=causal)
            if self.layer_sh.checkpoint:
                fn = M.remat(fn, cfg)
            x = fn(lp, x)
        return x

    def _stacked_entry(self, embed_p, x_in, tokens, lane_keys):
        """Stage input: lane 0 embeds the tick's tokens, others take the
        rotated activation. The embedding is lane-invariant (computed once
        and broadcast) unless dropout is on, in which case each lane embeds
        with its own key — matching the old vmapped trace exactly."""
        cfg = self.cfg
        if lane_keys is None:
            emb = M.apply_embedding(
                embed_p, tokens, cfg,
                compute_dtype=self.compute_dtype)[None]
        else:
            ek = jax.vmap(lambda kk: jax.random.fold_in(
                kk, M.DROPOUT_STREAM_EMBED))(lane_keys)
            emb = jax.vmap(lambda kk: M.apply_embedding(
                embed_p, tokens, cfg, compute_dtype=self.compute_dtype,
                dropout_rng=kk))(ek)
        lane0 = (jnp.arange(self.pp) == 0)[:, None, None, None]
        return jnp.where(lane0, emb, x_in)

    def _stacked_fwd(self, stages_w, embed_p, x_in, tokens, mbs, step_rng):
        lane_keys = self._lane_keys(step_rng, mbs)
        x = self._stacked_entry(embed_p, x_in, tokens, lane_keys)
        return self._stacked_layers(stages_w, x, lane_keys)

    def _stacked_full(self, stages_w, shared, x_in, tokens, labels, mask,
                      mbs, step_rng):
        """Stage forward INCLUDING the head: returns (y_out, [pp] losses).
        Used by backward ticks (the vjp recomputes the stage from its
        stored input, per-stage remat) and by eval. Only the last lane's
        loss ever receives a non-zero cotangent / enters the loss
        accumulator; the vocab weights are replicated across pp, so the
        head segment is a plain per-lane vmap."""
        cfg = self.cfg
        lane_keys = self._lane_keys(step_rng, mbs)
        x = self._stacked_entry(shared["embed"], x_in, tokens, lane_keys)
        y = self._stacked_layers(stages_w, x, lane_keys)
        h = M.apply_norm(shared["prenorm"], y, cfg)
        wte = (shared["embed"]["wte"]
               if cfg.tie_word_embeddings else None)
        head = shared["head"]

        def lane_loss(hh):
            logits = M.apply_lm_head(head, hh, cfg, wte=wte,
                                     compute_dtype=self.compute_dtype)
            return M.cross_entropy_loss(logits, labels, mask)

        return y, jax.vmap(lane_loss)(h)

    # ------------------------------------------------------------------
    # the fused step
    # ------------------------------------------------------------------

    def _schedule_constants(self, m: int):
        pp = self.pp
        T = m + 2 * (pp - 1)
        D = 2 * pp - 1  # circular input-buffer depth (see module docstring)
        return T, D

    def bubble_frac(self, m: Optional[int] = None) -> float:
        """Idle fraction of the lockstep schedule: each lane does 2m work
        units over T = m + 2(pp-1) ticks of 2 slots each."""
        m = max(m if m is not None else self.hpc.chunks, 1)
        return (2.0 * (self.pp - 1)) / (m + 2 * (self.pp - 1))

    def _build_step(self, m: int, use_dropout: bool):
        cfg = self.cfg
        pp, lps = self.pp, self.lps
        T, D = self._schedule_constants(m)
        mesh = self.mesh
        act_sp = stacked_spec(self.layer_sh.act_spec())
        rot_fwd = make_pp_rotation(mesh, act_sp, +1)
        rot_bwd = make_pp_rotation(mesh, act_sp, -1)
        act_shd = NamedSharding(mesh, act_sp)
        lanes = np.arange(pp)
        clip = self.train.clip_grad
        tx = self.tx

        from hetu_galvatron_tpu.parallel.spmd import make_embed_use_constraint

        # forward-side hint only: under ZeRO-3 the gathered table must not
        # re-materialize per use site (parallel/spmd.py)
        axes_embed = getattr(self, "_embed_axes", None)
        constrain_embed = (
            make_embed_use_constraint(axes_embed, self.vocab_sh, mesh)
            if axes_embed is not None else (lambda e: e))

        # de-vmapped stage programs: ordinary traced code over the stacked
        # [pp, ...] stream — which is what lets the shard_map kernels
        # (ring matmuls / flash / ulysses / cp) run inside the scan
        vfwd = self._stacked_fwd
        vfull = self._stacked_full

        hier = self._hier

        def step(sp, opt, batch, step_rng):
            tokens = batch["tokens"]            # [m, B, S] int32
            labels = batch["labels"]            # [m, B, S] int32
            mask = batch.get("loss_mask")       # [m, B, S] f32 or absent
            weights = microbatch_weights(mask, m)
            shared = {"embed": constrain_embed(sp["embed"]),
                      "prenorm": sp["prenorm"], "head": sp["head"]}
            stages_w = sp["stages"]
            b, s = tokens.shape[1], tokens.shape[2]
            zero_act = jnp.zeros((pp, b, s, cfg.hidden_size),
                                 self.compute_dtype)
            if hier is None:
                gacc0 = jax.tree.map(
                    lambda p: jnp.zeros(p.shape, p.dtype),
                    {"stages": stages_w, **shared})
            else:
                # lane-stacked fp32 accumulators: [L, ...] with the lane
                # dim on the dp mesh axes — per-device memory equals the
                # flat accumulator's (each device holds one lane's slice)
                gacc0 = hier.constrain_stacked(jax.tree.map(
                    lambda p: jnp.zeros((hier.lanes,) + p.shape,
                                        jnp.float32),
                    {"stages": stages_w, **shared}))
            buf0 = jnp.zeros((pp, D, b, s, cfg.hidden_size),
                             self.compute_dtype)
            lanes_a = jnp.asarray(lanes)

            def idx(arr, i):
                return jax.lax.dynamic_index_in_dim(
                    arr, jnp.clip(i, 0, m - 1), 0, keepdims=False)

            def tick(carry, t):
                fwd_x, bwd_dy, buf, gacc, loss_acc = carry
                # ---- forward unit: stage s runs microbatch i = t - s ----
                fi = t - lanes_a
                tok_f = idx(tokens, t)  # lane 0's fwd microbatch is t
                # store the PRE-apply stage inputs (the backward recomputes
                # from them); raw-fi slots make out-of-range writes land on
                # provably-dead slots (module docstring), so no gating read
                slot_f = jnp.mod(fi, D)
                buf = jax.vmap(
                    lambda bl, x, i: jax.lax.dynamic_update_index_in_dim(
                        bl, x, i, 0))(buf, fwd_x, slot_f)
                y = vfwd(stages_w, shared["embed"], fwd_x, tok_f,
                         jnp.clip(fi, 0, m - 1), step_rng)
                y = jax.lax.with_sharding_constraint(y, act_shd)
                # ---- backward unit: stage s runs mb j = t - 2(pp-1) + s ----
                bj = t - 2 * (pp - 1) + lanes_a
                bwd_valid = (bj >= 0) & (bj < m)
                slot_b = jnp.mod(bj, D)
                x_st = jax.vmap(
                    lambda bl, i: jax.lax.dynamic_index_in_dim(
                        bl, i, 0, keepdims=False))(buf, slot_b)
                tok_b = idx(tokens, bj[0])        # lane 0 re-embeds
                lbl_b = idx(labels, bj[pp - 1])   # last lane's CE target
                msk_b = idx(mask, bj[pp - 1]) if mask is not None else None
                w_b = idx(weights, bj[pp - 1])

                # bubble masking: zero cotangent seeds on invalid lanes
                # make EVERY grad they emit exactly zero (vjp linearity)
                dy_in = jnp.where(bwd_valid[:, None, None, None], bwd_dy,
                                  jnp.zeros_like(bwd_dy))
                if hier is None:
                    (y_re, losses), vjp_fn = jax.vjp(
                        lambda ws, sh, xs: vfull(
                            ws, sh, xs, tok_b, lbl_b, msk_b,
                            jnp.clip(bj, 0, m - 1), step_rng),
                        stages_w, shared, x_st)
                    dl_in = jnp.where(
                        (lanes_a == pp - 1) & bwd_valid,
                        w_b.astype(jnp.float32), 0.0)
                    dws, dsh, dxs = vjp_fn((dy_in, dl_in))
                    gacc = jax.tree.map(jnp.add, gacc,
                                        {"stages": dws, **dsh})
                    loss_acc = loss_acc + jnp.where(
                        bwd_valid[pp - 1], w_b * losses[pp - 1], 0.0)
                else:
                    # per-dp-lane backward: the vjp runs vmapped over the
                    # lane-split batch slices (stage weights unmapped), so
                    # per-lane grads stack [L, ...] and accumulate with
                    # ZERO cross-dp bytes; the hierarchical reduce after
                    # the scan performs the only dp communication
                    L = hier.lanes
                    bl = b // L

                    def lanes_in(a):  # [pp, b, ...] -> [L, pp, b/L, ...]
                        y = a.reshape((a.shape[0], L, bl) + a.shape[2:])
                        return jnp.moveaxis(y, 1, 0)

                    def lanes_out(a):  # inverse of lanes_in
                        y = jnp.moveaxis(a, 0, 1)
                        return y.reshape((y.shape[0], b) + y.shape[3:])

                    tok_l = tok_b.reshape((L, bl) + tok_b.shape[1:])
                    lbl_l = lbl_b.reshape((L, bl) + lbl_b.shape[1:])
                    msk_l = (msk_b.reshape((L, bl) + msk_b.shape[1:])
                             if msk_b is not None else None)
                    # per-lane token share of THIS microbatch: the
                    # weighted lane means recombine to the flat path's
                    # microbatch masked mean exactly
                    share = microbatch_weights(msk_l, L)
                    w_lane = w_b.astype(jnp.float32) * share

                    def lane_bwd(xs_l, dy_l, tok_i, lbl_i, msk_i, w_i):
                        (y_re, losses), vjp_fn = jax.vjp(
                            lambda ws, sh, xs: vfull(
                                ws, sh, xs, tok_i, lbl_i, msk_i,
                                jnp.clip(bj, 0, m - 1), step_rng),
                            stages_w, shared, xs_l)
                        dl = jnp.where((lanes_a == pp - 1) & bwd_valid,
                                       w_i, 0.0)
                        dws, dsh, dxs = vjp_fn((dy_l, dl))
                        return dws, dsh, dxs, losses

                    # spmd_axis_name pins the lane axis of every batched
                    # intermediate onto the dp mesh axes (ops/hier_reduce
                    # lane discipline — the per-lane slices never leave
                    # their dp group)
                    dws, dsh, dxs_l, losses = jax.vmap(
                        lane_bwd,
                        in_axes=(0, 0, 0, 0,
                                 0 if msk_l is not None else None, 0),
                        spmd_axis_name=tuple(self.layer_sh.dp_axes))(
                        lanes_in(x_st), lanes_in(dy_in), tok_l, lbl_l,
                        msk_l, w_lane)
                    gacc = hier.constrain_stacked(jax.tree.map(
                        lambda a, g: a + g.astype(jnp.float32), gacc,
                        {"stages": dws, **dsh}))
                    dxs = lanes_out(dxs_l).astype(self.compute_dtype)
                    loss_acc = loss_acc + jnp.where(
                        bwd_valid[pp - 1],
                        jnp.sum(w_lane * losses[:, pp - 1]), 0.0)
                # ---- rotate: activations s->s+1, cotangents s->s-1 ----
                fwd_x = rot_fwd(y)
                dxs = jax.lax.with_sharding_constraint(dxs, act_shd)
                bwd_dy = rot_bwd(dxs)
                return (fwd_x, bwd_dy, buf, gacc, loss_acc), None

            carry0 = (zero_act, zero_act, buf0, gacc0,
                      jnp.zeros((), jnp.float32))
            (_, _, _, grads, loss), _ = jax.lax.scan(
                tick, carry0, jnp.arange(T))
            if hier is not None:
                # the ONLY dp communication of the step: rs-intra at full
                # volume, ar-cross on the 1/intra shard, ag-intra back
                grads = hier.reduce(grads)

            # global grad-norm clip fused into the program (host engine:
            # _gnorm_jit/_clip_jit across submeshes). The single wte already
            # counts the tied grads once — no double-count correction.
            sq = sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                     for g in jax.tree.leaves(grads))
            gnorm = jnp.sqrt(sq)
            scale = (jnp.minimum(1.0, clip / (gnorm + 1e-12))
                     if clip and clip > 0 else jnp.ones((), jnp.float32))
            grads = jax.tree.map(lambda g: g * scale, grads)
            updates, new_opt = tx.update(grads, opt, sp)
            new_sp = optax.apply_updates(sp, updates)
            return new_sp, new_opt, {"loss": loss, "grad_norm": gnorm}

        # out_shardings pin the step to a FIXED POINT of its own layouts:
        # without them the first call's propagated outputs differ from the
        # split_params placement and the second call would recompile
        out_shd = (getattr(self, "_param_shardings", None),
                   getattr(self, "_opt_shardings", None), None)
        jit_kw = dict(donate_argnums=(0, 1) if self.donate else ())
        if out_shd[0] is not None and out_shd[1] is not None:
            jit_kw["out_shardings"] = out_shd
        if not use_dropout:
            step_nr = lambda sp, opt, batch: step(sp, opt, batch, None)
            return jax.jit(step_nr, **jit_kw)
        return jax.jit(step, **jit_kw)

    def _build_eval(self, m: int):
        """Forward-only compiled schedule: T = m + pp - 1 ticks, loss
        accumulated from the last lane (dropout off — eval semantics)."""
        cfg = self.cfg
        pp = self.pp
        mesh = self.mesh
        act_sp = stacked_spec(self.layer_sh.act_spec())
        rot_fwd = make_pp_rotation(mesh, act_sp, +1)
        act_shd = NamedSharding(mesh, act_sp)
        lanes = np.arange(pp)

        def vfull(stages_w, shared, x_stack, tokens, labels, mask, mbs):
            return self._stacked_full(stages_w, shared, x_stack, tokens,
                                      labels, mask, mbs, None)

        def eval_step(sp, batch):
            tokens, labels = batch["tokens"], batch["labels"]
            mask = batch.get("loss_mask")
            weights = microbatch_weights(mask, m)
            shared = {"embed": sp["embed"], "prenorm": sp["prenorm"],
                      "head": sp["head"]}
            b, s = tokens.shape[1], tokens.shape[2]
            zero_act = jnp.zeros((pp, b, s, cfg.hidden_size),
                                 self.compute_dtype)
            lanes_a = jnp.asarray(lanes)

            def idx(arr, i):
                return jax.lax.dynamic_index_in_dim(
                    arr, jnp.clip(i, 0, m - 1), 0, keepdims=False)

            def tick(carry, t):
                fwd_x, loss_acc = carry
                fi = t - lanes_a
                li = t - (pp - 1)  # last lane's microbatch this tick
                y, losses = vfull(sp["stages"], shared, fwd_x, idx(tokens, t),
                                  idx(labels, li), idx(mask, li)
                                  if mask is not None else None,
                                  jnp.clip(fi, 0, m - 1))
                loss_acc = loss_acc + jnp.where(
                    (li >= 0) & (li < m), idx(weights, li) * losses[pp - 1],
                    0.0)
                y = jax.lax.with_sharding_constraint(y, act_shd)
                return (rot_fwd(y), loss_acc), None

            (_, loss), _ = jax.lax.scan(
                tick, (zero_act, jnp.zeros((), jnp.float32)),
                jnp.arange(m + pp - 1))
            return loss

        return jax.jit(eval_step)

    # ------------------------------------------------------------------
    # public step API (PipelineEngine-compatible)
    # ------------------------------------------------------------------

    def put_batch(self, batch: Dict[str, np.ndarray], m: int
                  ) -> Dict[str, jax.Array]:
        """Host batch -> stacked [m, B, S] device arrays under the plan's
        batch sharding. The ONLY per-step host->device transfer of the
        steady state (the schedule's indices, weights and schedule masks
        are all program constants)."""
        allowed = {"tokens", "labels", "loss_mask"}
        extra = set(batch) - allowed - {"dropout_rng"}
        if extra:
            raise NotImplementedError(
                f"the compiled pipeline schedule does not thread batch keys "
                f"{sorted(extra)}")
        b = batch["tokens"].shape[0]
        if b % m:
            raise ValueError(f"batch {b} not divisible by chunks {m}")
        spec = self.vocab_sh.batch_spec()
        shd = NamedSharding(self.mesh, P(None, *spec))
        out = {}
        for k in allowed & set(batch):
            v = np.asarray(batch[k])
            out[k] = jax.device_put(
                v.reshape((m, b // m) + v.shape[1:]), shd)
        return out

    def _resolve_m(self, num_microbatches: Optional[int]) -> int:
        return max(num_microbatches if num_microbatches is not None
                   else self.hpc.chunks, 1)

    def train_step(
        self,
        sp: Params,
        opt: Any,
        batch: Dict[str, np.ndarray],
        num_microbatches: Optional[int] = None,
    ) -> Tuple[Params, Any, Dict[str, Any]]:
        """One fused optimizer step. ``batch`` may be a raw host batch
        ([gbsz, ...] numpy) or the output of :meth:`put_batch` (stacked
        device arrays — zero transfers besides the feed). Metrics stay lazy
        device scalars (no host sync on the step path)."""
        m = self._resolve_m(num_microbatches)
        batch = dict(batch)
        step_rng = batch.pop("dropout_rng", None)
        if self._use_dropout and step_rng is None:
            raise ValueError(
                "cfg enables dropout but the batch has no 'dropout_rng' "
                "key; train_loop/cli add it automatically — manual callers "
                "must pass one per step")
        # .ndim only — np.asarray on a staged device batch would pull the
        # whole token array back to the host every step
        if batch["tokens"].ndim == 2:
            batch = self.put_batch(batch, m)
        if m not in self._step_jits:
            self._step_jits[m] = self._build_step(m, self._use_dropout)
        fn = self._step_jits[m]
        # XLA-counted flops/bytes for the fused program (cost/* gauges;
        # no-op without a metrics sink). BEFORE the call: the step donates
        # (sp, opt, batch), and lowering only reads avals
        maybe_record_jit_cost(
            f"pp/compiled_step_m{m}", fn,
            (sp, opt, batch, step_rng) if self._use_dropout
            else (sp, opt, batch))
        with span("pp/compiled_step"):
            if self._use_dropout:
                out = fn(sp, opt, batch, step_rng)
            else:
                out = fn(sp, opt, batch)
        get_registry().gauge("pp/bubble_frac").set(self.bubble_frac(m))
        return out

    def eval_step(
        self,
        sp: Params,
        batch: Dict[str, np.ndarray],
        num_microbatches: Optional[int] = None,
    ) -> Dict[str, float]:
        """Held-out loss under the training plan (dropout off)."""
        m = self._resolve_m(num_microbatches)
        batch = dict(batch)
        batch.pop("dropout_rng", None)
        if batch["tokens"].ndim == 2:
            batch = self.put_batch(batch, m)
        if m not in self._eval_jits:
            self._eval_jits[m] = self._build_eval(m)
        return {"loss": float(self._eval_jits[m](sp, batch))}

    def _prep_trace(self, sp: Params, opt: Any,
                    batch: Dict[str, np.ndarray],
                    num_microbatches: Optional[int]):
        """Shared trace-entry prep for :meth:`step_jaxpr` /
        :meth:`step_lowered`: resolve the microbatch count, validate and
        pop the dropout rng, stage the batch, and fill the step-jit
        cache. Returns ``(fn, args)`` ready to trace or lower."""
        m = self._resolve_m(num_microbatches)
        batch = dict(batch)
        step_rng = batch.pop("dropout_rng", None)
        if self._use_dropout and step_rng is None:
            raise ValueError(
                "cfg enables dropout but the batch has no 'dropout_rng' "
                "key; train_loop/cli add it automatically — manual callers "
                "must pass one per step")
        if batch["tokens"].ndim == 2:
            batch = self.put_batch(batch, m)
        if m not in self._step_jits:
            self._step_jits[m] = self._build_step(m, self._use_dropout)
        fn = self._step_jits[m]
        args = (sp, opt, batch, step_rng) if self._use_dropout \
            else (sp, opt, batch)
        return fn, args

    def step_jaxpr(self, sp: Params, opt: Any, batch: Dict[str, np.ndarray],
                   num_microbatches: Optional[int] = None):
        """ClosedJaxpr of the fused step program — the static-analysis hook
        (``analysis/census.py``). Tracing never executes and never consumes
        donated buffers, so this is safe before (or instead of) any real
        step; the traced fn is cached in the step-jit cache, so a later
        ``train_step`` at the same microbatch count reuses it."""
        fn, args = self._prep_trace(sp, opt, batch, num_microbatches)
        return jax.make_jaxpr(fn)(*args)

    def step_lowered(self, sp: Params, opt: Any,
                     batch: Dict[str, np.ndarray],
                     num_microbatches: Optional[int] = None):
        """``jax.stages.Lowered`` of the fused step — the partition-time
        static-analysis hook (``analysis/sharding_flow.py`` compiles it
        and scans the HLO for GSPMD-inserted collectives). Lowering reads
        avals only; nothing executes and no donated buffer is consumed.
        Compiling the returned object is the expensive part — callers on
        the fast path should stick to :meth:`step_jaxpr`."""
        fn, args = self._prep_trace(sp, opt, batch, num_microbatches)
        return fn.lower(*args)

    def compile_count(self) -> int:
        """Total compiled executables across the engine's jit caches — the
        recompile-pinning hook (serving engine convention): steady state
        must hold this constant."""
        return sum(f._cache_size()
                   for f in (*self._step_jits.values(),
                             *self._eval_jits.values()))
