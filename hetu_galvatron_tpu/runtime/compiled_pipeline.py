"""Compiled pipeline schedule: the whole 1F1B step as ONE SPMD program.

The host engine (:mod:`runtime.pipeline`) sequences its schedule from the
host — one jitted call per (stage, microbatch) leg, ~375 us of dispatch each
(PERF.md round 5) and no *guaranteed* device overlap. This module is the
idiomatic XLA answer to VERDICT r4 weak #5: compile the ENTIRE 1F1B schedule
(warmup forwards, steady-state one-forward-one-backward, cooldown backwards,
gradient accumulation, tied-embedding grad exchange, global-norm clip and the
optimizer update) into a single GSPMD program over a full ``(pp, d0..dk)``
mesh, so XLA's latency-hiding scheduler overlaps the inter-stage transfers
with compute ("The Big Send-off", PAPERS.md).

Layout and mechanics:

* **One mesh, real pp axis** — ``build_mesh(world, pp)`` instead of the host
  engine's disjoint per-stage submeshes. Per-stage decoder weights are
  STACKED along a leading ``[pp, ...]`` axis sharded on the ``pp`` mesh axis
  (``mesh.stacked_spec``), so stage s's slice physically lives on mesh row s.
  The vocab layers (embed / prenorm / head) are replicated across ``pp``
  rows; replication + psum-through-autodiff is what fuses the tied-embedding
  grad exchange into the program (see below).
* **Lockstep tick scan** — a `lax.scan` over ``T = m + 2(pp-1)`` schedule
  ticks (m microbatches). At tick t, stage s runs the FORWARD of microbatch
  ``i = t - s`` (when ``0 <= i < m``) and the BACKWARD of microbatch
  ``j = t - 2(pp-1) + s``; both units execute as ONE vmapped computation
  over the stacked stage axis, which GSPMD partitions along ``pp`` — every
  mesh row computes only its own stage. Bubble ticks are masked by zeroing
  the backward cotangent seeds (zero cotangent in => exactly-zero grads out,
  by linearity of the vjp) and by `where`-gating the loss/grad accumulators.
* **collective-permute stage transfers** — activations rotate ``s -> s+1``
  and cotangents ``s -> s-1`` with `lax.ppermute` over the ``pp`` axis
  (``mesh.make_pp_rotation``), the compiled analogue of the reference's
  batched isend/irecv and of the host engine's `jax.device_put` hops.
* **1F1B memory bound** — the backward recomputes its stage forward from the
  stored stage INPUT (`jax.vjp`, per-stage remat — same policy as the host
  engine), so each stage keeps a circular buffer of ``2*pp - 1`` in-flight
  stage inputs: O(pp), independent of the microbatch count (GPipe would be
  O(m)). The depth-``2pp-1`` bound (vs the host schedule's ``pp``) is the
  price of the lockstep fwd+bwd tick; slot reuse is provably collision-free
  because a slot distance of a full buffer length can never separate two
  live microbatches of one stage.
* **Tied embeddings for free** — the last stage's logits use ``wte.T``
  directly (the table is replicated across ``pp``), so autodiff SUMS the
  embedding-lookup grad (stage 0's lane) and the head grad (last lane) into
  one ``wte`` cotangent — the host engine's explicit transpose-and-exchange
  becomes a psum the partitioner places.
* **Redundant vocab compute** — under the vmapped lockstep tick every mesh
  row also executes the (masked) head matmul in backward ticks; only the
  last row's result carries a non-zero cotangent. This trades ~one
  layer-equivalent of per-tick compute for a schedule with zero host
  dispatch; the embedding lookup itself is batched OUT of the vmap (its
  inputs are lane-invariant) and costs nothing extra.

Eligibility (everything else falls back to the host engine, which stays the
general path): causal-LM / bert families (no t5 pair carry), vpp=1, uniform
``pp_division`` and a uniform per-layer strategy (stacking needs one shard
layout), no MoE, no context parallelism / packed-document fields. Attention
runs the XLA core inside the program (the Pallas flash / ring kernels are
shard_map programs that cannot nest under the stacked vmap); the
`tools/pipeline_dispatch_bench.py` A/B leg measures what that trade buys.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from hetu_galvatron_tpu.core.args_schema import ModelArgs, TrainArgs
from hetu_galvatron_tpu.models import modules as M
from hetu_galvatron_tpu.observability.registry import get_registry
from hetu_galvatron_tpu.observability.trace_analysis import (
    maybe_record_jit_cost,
)
from hetu_galvatron_tpu.observability.tracing import span
from hetu_galvatron_tpu.runtime.hybrid_config import HybridParallelConfig
from hetu_galvatron_tpu.runtime.mesh import (
    build_mesh,
    lower_strategy,
    lower_vocab_strategy,
    make_pp_rotation,
    spec_tree,
    stacked_spec,
)
from hetu_galvatron_tpu.runtime.trainer import microbatch_weights

Params = Dict[str, Any]


def _stacked_decay_mask(params: Params) -> Params:
    """Weight-decay mask for the stacked layout: the plain rule is
    ``ndim >= 2`` (runtime/optimizer.py `_decay_mask`), but ``stages`` leaves
    carry a leading ``[pp]`` stage axis that must not promote a stacked bias
    into a decayed "matrix"."""
    return {
        k: jax.tree.map(
            lambda p, off=(1 if k == "stages" else 0): p.ndim - off >= 2, v)
        for k, v in params.items()
    }


def _compiled_optimizer(train: TrainArgs) -> optax.GradientTransformation:
    """Host-parity optimizer (pipeline._pipeline_optimizer: Adam + wd +
    schedule WITHOUT the global clip — the clip scale is applied explicitly
    so it is global across stages) with the stacking-aware decay mask."""
    from hetu_galvatron_tpu.runtime.optimizer import (
        make_lr_schedule,
        partition_expert_bias,
    )

    chain = [optax.scale_by_adam(b1=train.adam_beta1, b2=train.adam_beta2,
                                 eps=train.adam_eps)]
    if train.weight_decay:
        chain.append(optax.add_decayed_weights(train.weight_decay,
                                               mask=_stacked_decay_mask))
    chain.append(optax.scale_by_learning_rate(make_lr_schedule(train)))
    return partition_expert_bias(optax.chain(*chain))


class CompiledPipelineEngine:
    """Single-program 1F1B: same external contract as ``PipelineEngine``
    (split_params / init_opt / train_step / eval_step / merge_params), but
    params are one pp-stacked tree instead of a list of per-stage trees and
    the whole optimizer step is one donated jit call."""

    @staticmethod
    def unsupported_reason(cfg: ModelArgs, hpc: HybridParallelConfig,
                           data: Any = None) -> Optional[str]:
        """None when the compiled schedule can express this plan; otherwise
        a human-readable reason the launcher logs before falling back to the
        host engine."""
        if hpc.pp_deg < 2:
            return "pp_deg < 2 routes through the SPMD path"
        if hpc.pipeline_type != "pipedream_flush":
            return "compiled schedule implements 1F1B (pipedream_flush) only"
        if getattr(hpc, "vpp_deg", 1) > 1:
            return "interleaved virtual stages (vpp > 1)"
        if cfg.model_type == "t5":
            return "encoder-decoder (a, b) pair carry"
        if cfg.num_experts:
            return "MoE layers alternate tree structures across the stack"
        if len(set(hpc.pp_division)) != 1:
            return (f"heterogeneous per-stage layer counts "
                    f"{hpc.pp_division} (stage stacking needs uniformity)")
        if any(s != hpc.layers[0] for s in hpc.layers):
            return "heterogeneous per-layer strategies"
        if hpc.layers[0].cp_size > 1 or hpc.vocab.vcp > 1:
            return "context parallelism (ring attention is a shard_map kernel)"
        if getattr(hpc, "cp_zigzag", False):
            return "zigzag cp data layout"
        if data is not None and (getattr(data, "reset_position_ids", False)
                                 or getattr(data, "reset_attention_mask",
                                            False)):
            return "packed-document position/segment fields"
        return None

    def __init__(
        self,
        cfg: ModelArgs,
        hpc: HybridParallelConfig,
        train: TrainArgs,
        devices: Optional[List] = None,
        *,
        compute_dtype=jnp.bfloat16,
        dcn_slices: int = 1,
        donate: bool = True,
    ):
        reason = self.unsupported_reason(cfg, hpc)
        if reason is not None:
            raise ValueError(f"compiled pipeline schedule unsupported: "
                             f"{reason}")
        self.cfg = cfg
        self.hpc = hpc
        self.train = train
        self.compute_dtype = compute_dtype
        self.donate = donate
        self.pp = hpc.pp_deg
        self.lps = hpc.pp_division[0]  # layers per stage (uniform)
        devices = list(devices if devices is not None else jax.devices())
        if len(devices) < hpc.world_size:
            raise ValueError(
                f"need {hpc.world_size} devices, have {len(devices)}")
        self.mesh = build_mesh(hpc.world_size, self.pp,
                               devices=devices[:hpc.world_size],
                               dcn_slices=dcn_slices)
        self.layer_sh = lower_strategy(hpc.layers[0], self.mesh)
        self.vocab_sh = lower_vocab_strategy(hpc.vocab, self.mesh,
                                             hpc.default_dp_type)
        self.tx = _compiled_optimizer(train)
        self._use_dropout = (cfg.hidden_dropout > 0.0
                             or cfg.attention_dropout > 0.0)
        # jit caches keyed by microbatch count (a batch-size ramp compiles
        # one program per distinct count; a fixed plan compiles exactly once)
        self._step_jits: Dict[int, Any] = {}
        self._eval_jits: Dict[int, Any] = {}

    # ------------------------------------------------------------------
    # params / optimizer state (stacked layout)
    # ------------------------------------------------------------------

    def _slot_axes(self, axes: Params, j: int) -> Params:
        """Logical-axis tree for stage-layer slot j (identical across
        stages under the uniform-strategy gate)."""
        return axes["layers"][j]

    def stacked_param_specs(self, axes: Params, opt: bool = False) -> Params:
        """PartitionSpec tree mirroring the stacked params: ``stages`` slot
        leaves get P('pp', *layer_spec); vocab-row leaves (embed / prenorm /
        head) keep the vocab sharding and replicate across pp."""
        isP = lambda x: isinstance(x, P)
        out: Params = {"stages": tuple(
            jax.tree.map(stacked_spec,
                         spec_tree(self._slot_axes(axes, j), self.layer_sh,
                                   opt),
                         is_leaf=isP)
            for j in range(self.lps))}
        for k in ("embed", "prenorm", "head"):
            out[k] = spec_tree(axes[k], self.vocab_sh, opt)
        return out

    def _nshd(self, spec_tree_: Any) -> Any:
        return jax.tree.map(lambda s: NamedSharding(self.mesh, s),
                            spec_tree_, is_leaf=lambda x: isinstance(x, P))

    def split_params(self, params: Params, axes: Params) -> Params:
        """Full (host/single-device) params tree -> the stacked layout:
        decoder layer ``s*lps + j`` becomes row s of ``stages[j]``; the
        vocab rows are placed replicated across pp. The tied head carries NO
        transposed copy — the program reads ``wte.T`` directly."""
        n = self.pp * self.lps
        if len(params["layers"]) != n:
            raise ValueError(f"params have {len(params['layers'])} layers, "
                             f"plan has {n}")
        stages = tuple(
            jax.tree.map(lambda *leaves: jnp.stack(leaves),
                         *[params["layers"][s * self.lps + j]
                           for s in range(self.pp)])
            for j in range(self.lps))
        sp: Params = {"stages": stages, "embed": params["embed"],
                      "prenorm": params["prenorm"], "head": params["head"]}
        # remember the embed's logical axes so the step program can state
        # the ZeRO-3 use-site gather explicitly (spmd
        # make_embed_use_constraint); without it the program is still
        # correct, just chattier to partition
        self._embed_axes = axes["embed"]
        specs = self.stacked_param_specs(axes)
        self._param_shardings = self._nshd(specs)
        # stage through a host copy: device_put of a fully-replicated leaf
        # can ALIAS the caller's buffer, and the donated step would then
        # delete the caller's params out from under it
        return jax.tree.map(
            lambda p, s: jax.device_put(np.asarray(p),
                                        NamedSharding(self.mesh, s)),
            sp, specs)

    def merge_params(self, sp: Params) -> Params:
        """Stacked layout -> the full host tree (tests / checkpointing),
        matching ``PipelineEngine.merge_params`` output structure."""
        stages = jax.device_get(sp["stages"])
        layers: List[Params] = []
        for s in range(self.pp):
            for j in range(self.lps):
                layers.append(jax.tree.map(lambda x: np.asarray(x)[s],
                                           stages[j]))
        return {"layers": tuple(layers),
                "embed": jax.device_get(sp["embed"]),
                "prenorm": jax.device_get(sp["prenorm"]),
                "head": jax.device_get(sp["head"])}

    def init_opt(self, sp: Params, axes: Params) -> Any:
        from hetu_galvatron_tpu.parallel.spmd import opt_state_specs

        opt_pspecs = self.stacked_param_specs(axes, opt=True)
        specs = opt_state_specs(self.tx, sp, opt_pspecs)
        self._opt_shardings = self._nshd(specs)
        init = jax.jit(self.tx.init, out_shardings=self._opt_shardings)
        return init(sp)

    # ------------------------------------------------------------------
    # lane programs (vmapped over the stacked stage axis)
    # ------------------------------------------------------------------

    def _lane_rng(self, step_rng, mb, lane):
        """Per-(microbatch, stage) dropout key — same derivation as the host
        engine's ``_mb_rng`` so a compiled run replays identical masks."""
        if step_rng is None:
            return None
        return jax.random.fold_in(jax.random.fold_in(step_rng, mb), lane)

    def _apply_stage_layers(self, stage_w, x, lane_rng):
        """The Lps decoder layers of one lane (per-layer remat honored)."""
        cfg = self.cfg
        rope = None
        if cfg.position_embedding_type == "rope":
            cos, sin = M.rope_cos_sin(x.shape[1], cfg.head_dim,
                                      cfg.rope_theta,
                                      scaling=cfg.rope_scaling)
            rope = (cos, sin)
        for j, lp in enumerate(stage_w):
            fn = partial(M.apply_decoder_layer, cfg=cfg, rope=rope,
                         compute_dtype=self.compute_dtype,
                         dropout_rng=M.fold_dropout_rng(lane_rng, cfg, j))
            if self.layer_sh.checkpoint:
                fn = M.remat(fn, cfg)
            x = fn(lp, x)
        return x

    def _lane_entry(self, embed_p, x_in, tokens, lane, lane_rng):
        """Stage input: lane 0 embeds the tick's tokens, others take the
        rotated activation. The embedding itself is lane-invariant (tokens
        and table are broadcast into the vmap), so vmap batches it OUT of
        the per-lane work — only the select is per-lane."""
        emb = M.apply_embedding(
            embed_p, tokens, self.cfg, compute_dtype=self.compute_dtype,
            dropout_rng=M.fold_dropout_rng(
                lane_rng, self.cfg, M.DROPOUT_STREAM_EMBED))
        return jnp.where(lane == 0, emb, x_in)

    def _lane_fwd(self, stage_w, embed_p, x_in, tokens, lane, mb, step_rng):
        lane_rng = self._lane_rng(step_rng, mb, lane)
        x = self._lane_entry(embed_p, x_in, tokens, lane, lane_rng)
        return self._apply_stage_layers(stage_w, x, lane_rng)

    def _lane_full(self, stage_w, shared, x_in, tokens, labels, mask, lane,
                   mb, step_rng):
        """Stage forward INCLUDING the head: returns (y_out, loss). Used by
        backward ticks (the vjp recomputes the stage from its stored input,
        per-stage remat) and by eval. Only the last lane's loss ever
        receives a non-zero cotangent / enters the loss accumulator."""
        lane_rng = self._lane_rng(step_rng, mb, lane)
        x = self._lane_entry(shared["embed"], x_in, tokens, lane, lane_rng)
        y = self._apply_stage_layers(stage_w, x, lane_rng)
        h = M.apply_norm(shared["prenorm"], y, self.cfg)
        wte = (shared["embed"]["wte"]
               if self.cfg.tie_word_embeddings else None)
        logits = M.apply_lm_head(shared["head"], h, self.cfg, wte=wte,
                                 compute_dtype=self.compute_dtype)
        loss = M.cross_entropy_loss(logits, labels, mask)
        return y, loss

    # ------------------------------------------------------------------
    # the fused step
    # ------------------------------------------------------------------

    def _schedule_constants(self, m: int):
        pp = self.pp
        T = m + 2 * (pp - 1)
        D = 2 * pp - 1  # circular input-buffer depth (see module docstring)
        return T, D

    def bubble_frac(self, m: Optional[int] = None) -> float:
        """Idle fraction of the lockstep schedule: each lane does 2m work
        units over T = m + 2(pp-1) ticks of 2 slots each."""
        m = max(m if m is not None else self.hpc.chunks, 1)
        return (2.0 * (self.pp - 1)) / (m + 2 * (self.pp - 1))

    def _build_step(self, m: int, use_dropout: bool):
        cfg = self.cfg
        pp, lps = self.pp, self.lps
        T, D = self._schedule_constants(m)
        mesh = self.mesh
        act_sp = stacked_spec(self.layer_sh.act_spec())
        rot_fwd = make_pp_rotation(mesh, act_sp, +1)
        rot_bwd = make_pp_rotation(mesh, act_sp, -1)
        act_shd = NamedSharding(mesh, act_sp)
        lanes = np.arange(pp)
        clip = self.train.clip_grad
        tx = self.tx

        from hetu_galvatron_tpu.parallel.spmd import make_embed_use_constraint

        # forward-side hint only: under ZeRO-3 the gathered table must not
        # re-materialize per use site (parallel/spmd.py)
        axes_embed = getattr(self, "_embed_axes", None)
        constrain_embed = (
            make_embed_use_constraint(axes_embed, self.vocab_sh, mesh)
            if axes_embed is not None else (lambda e: e))

        def vfwd(stages_w, embed_p, x_stack, tokens, mbs, step_rng):
            f = jax.vmap(self._lane_fwd,
                         in_axes=(0, None, 0, None, 0, 0, None))
            return f(stages_w, embed_p, x_stack, tokens, jnp.asarray(lanes),
                     mbs, step_rng)

        def vfull(stages_w, shared, x_stack, tokens, labels, mask, mbs,
                  step_rng):
            f = jax.vmap(self._lane_full,
                         in_axes=(0, None, 0, None, None, None, 0, 0, None))
            return f(stages_w, shared, x_stack, tokens, labels, mask,
                     jnp.asarray(lanes), mbs, step_rng)

        def step(sp, opt, batch, step_rng):
            tokens = batch["tokens"]            # [m, B, S] int32
            labels = batch["labels"]            # [m, B, S] int32
            mask = batch.get("loss_mask")       # [m, B, S] f32 or absent
            weights = microbatch_weights(mask, m)
            shared = {"embed": constrain_embed(sp["embed"]),
                      "prenorm": sp["prenorm"], "head": sp["head"]}
            stages_w = sp["stages"]
            b, s = tokens.shape[1], tokens.shape[2]
            zero_act = jnp.zeros((pp, b, s, cfg.hidden_size),
                                 self.compute_dtype)
            gacc0 = jax.tree.map(
                lambda p: jnp.zeros(p.shape, p.dtype),
                {"stages": stages_w, **shared})
            buf0 = jnp.zeros((pp, D, b, s, cfg.hidden_size),
                             self.compute_dtype)
            lanes_a = jnp.asarray(lanes)

            def idx(arr, i):
                return jax.lax.dynamic_index_in_dim(
                    arr, jnp.clip(i, 0, m - 1), 0, keepdims=False)

            def tick(carry, t):
                fwd_x, bwd_dy, buf, gacc, loss_acc = carry
                # ---- forward unit: stage s runs microbatch i = t - s ----
                fi = t - lanes_a
                tok_f = idx(tokens, t)  # lane 0's fwd microbatch is t
                # store the PRE-apply stage inputs (the backward recomputes
                # from them); raw-fi slots make out-of-range writes land on
                # provably-dead slots (module docstring), so no gating read
                slot_f = jnp.mod(fi, D)
                buf = jax.vmap(
                    lambda bl, x, i: jax.lax.dynamic_update_index_in_dim(
                        bl, x, i, 0))(buf, fwd_x, slot_f)
                y = vfwd(stages_w, shared["embed"], fwd_x, tok_f,
                         jnp.clip(fi, 0, m - 1), step_rng)
                y = jax.lax.with_sharding_constraint(y, act_shd)
                # ---- backward unit: stage s runs mb j = t - 2(pp-1) + s ----
                bj = t - 2 * (pp - 1) + lanes_a
                bwd_valid = (bj >= 0) & (bj < m)
                slot_b = jnp.mod(bj, D)
                x_st = jax.vmap(
                    lambda bl, i: jax.lax.dynamic_index_in_dim(
                        bl, i, 0, keepdims=False))(buf, slot_b)
                tok_b = idx(tokens, bj[0])        # lane 0 re-embeds
                lbl_b = idx(labels, bj[pp - 1])   # last lane's CE target
                msk_b = idx(mask, bj[pp - 1]) if mask is not None else None
                w_b = idx(weights, bj[pp - 1])

                (y_re, losses), vjp_fn = jax.vjp(
                    lambda ws, sh, xs: vfull(
                        ws, sh, xs, tok_b, lbl_b, msk_b,
                        jnp.clip(bj, 0, m - 1), step_rng),
                    stages_w, shared, x_st)
                # bubble masking: zero cotangent seeds on invalid lanes
                # make EVERY grad they emit exactly zero (vjp linearity)
                dy_in = jnp.where(bwd_valid[:, None, None, None], bwd_dy,
                                  jnp.zeros_like(bwd_dy))
                dl_in = jnp.where(
                    (lanes_a == pp - 1) & bwd_valid,
                    w_b.astype(jnp.float32), 0.0)
                dws, dsh, dxs = vjp_fn((dy_in, dl_in))
                gacc = jax.tree.map(jnp.add, gacc,
                                    {"stages": dws, **dsh})
                loss_acc = loss_acc + jnp.where(
                    bwd_valid[pp - 1], w_b * losses[pp - 1], 0.0)
                # ---- rotate: activations s->s+1, cotangents s->s-1 ----
                fwd_x = rot_fwd(y)
                dxs = jax.lax.with_sharding_constraint(dxs, act_shd)
                bwd_dy = rot_bwd(dxs)
                return (fwd_x, bwd_dy, buf, gacc, loss_acc), None

            carry0 = (zero_act, zero_act, buf0, gacc0,
                      jnp.zeros((), jnp.float32))
            (_, _, _, grads, loss), _ = jax.lax.scan(
                tick, carry0, jnp.arange(T))

            # global grad-norm clip fused into the program (host engine:
            # _gnorm_jit/_clip_jit across submeshes). The single wte already
            # counts the tied grads once — no double-count correction.
            sq = sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                     for g in jax.tree.leaves(grads))
            gnorm = jnp.sqrt(sq)
            scale = (jnp.minimum(1.0, clip / (gnorm + 1e-12))
                     if clip and clip > 0 else jnp.ones((), jnp.float32))
            grads = jax.tree.map(lambda g: g * scale, grads)
            updates, new_opt = tx.update(grads, opt, sp)
            new_sp = optax.apply_updates(sp, updates)
            return new_sp, new_opt, {"loss": loss, "grad_norm": gnorm}

        # out_shardings pin the step to a FIXED POINT of its own layouts:
        # without them the first call's propagated outputs differ from the
        # split_params placement and the second call would recompile
        out_shd = (getattr(self, "_param_shardings", None),
                   getattr(self, "_opt_shardings", None), None)
        jit_kw = dict(donate_argnums=(0, 1) if self.donate else ())
        if out_shd[0] is not None and out_shd[1] is not None:
            jit_kw["out_shardings"] = out_shd
        if not use_dropout:
            step_nr = lambda sp, opt, batch: step(sp, opt, batch, None)
            return jax.jit(step_nr, **jit_kw)
        return jax.jit(step, **jit_kw)

    def _build_eval(self, m: int):
        """Forward-only compiled schedule: T = m + pp - 1 ticks, loss
        accumulated from the last lane (dropout off — eval semantics)."""
        cfg = self.cfg
        pp = self.pp
        mesh = self.mesh
        act_sp = stacked_spec(self.layer_sh.act_spec())
        rot_fwd = make_pp_rotation(mesh, act_sp, +1)
        act_shd = NamedSharding(mesh, act_sp)
        lanes = np.arange(pp)

        def vfull(stages_w, shared, x_stack, tokens, labels, mask, mbs):
            f = jax.vmap(self._lane_full,
                         in_axes=(0, None, 0, None, None, None, 0, 0, None))
            return f(stages_w, shared, x_stack, tokens, labels, mask,
                     jnp.asarray(lanes), mbs, None)

        def eval_step(sp, batch):
            tokens, labels = batch["tokens"], batch["labels"]
            mask = batch.get("loss_mask")
            weights = microbatch_weights(mask, m)
            shared = {"embed": sp["embed"], "prenorm": sp["prenorm"],
                      "head": sp["head"]}
            b, s = tokens.shape[1], tokens.shape[2]
            zero_act = jnp.zeros((pp, b, s, cfg.hidden_size),
                                 self.compute_dtype)
            lanes_a = jnp.asarray(lanes)

            def idx(arr, i):
                return jax.lax.dynamic_index_in_dim(
                    arr, jnp.clip(i, 0, m - 1), 0, keepdims=False)

            def tick(carry, t):
                fwd_x, loss_acc = carry
                fi = t - lanes_a
                li = t - (pp - 1)  # last lane's microbatch this tick
                y, losses = vfull(sp["stages"], shared, fwd_x, idx(tokens, t),
                                  idx(labels, li), idx(mask, li)
                                  if mask is not None else None,
                                  jnp.clip(fi, 0, m - 1))
                loss_acc = loss_acc + jnp.where(
                    (li >= 0) & (li < m), idx(weights, li) * losses[pp - 1],
                    0.0)
                y = jax.lax.with_sharding_constraint(y, act_shd)
                return (rot_fwd(y), loss_acc), None

            (_, loss), _ = jax.lax.scan(
                tick, (zero_act, jnp.zeros((), jnp.float32)),
                jnp.arange(m + pp - 1))
            return loss

        return jax.jit(eval_step)

    # ------------------------------------------------------------------
    # public step API (PipelineEngine-compatible)
    # ------------------------------------------------------------------

    def put_batch(self, batch: Dict[str, np.ndarray], m: int
                  ) -> Dict[str, jax.Array]:
        """Host batch -> stacked [m, B, S] device arrays under the plan's
        batch sharding. The ONLY per-step host->device transfer of the
        steady state (the schedule's indices, weights and schedule masks
        are all program constants)."""
        allowed = {"tokens", "labels", "loss_mask"}
        extra = set(batch) - allowed - {"dropout_rng"}
        if extra:
            raise NotImplementedError(
                f"the compiled pipeline schedule does not thread batch keys "
                f"{sorted(extra)}")
        b = batch["tokens"].shape[0]
        if b % m:
            raise ValueError(f"batch {b} not divisible by chunks {m}")
        spec = self.vocab_sh.batch_spec()
        shd = NamedSharding(self.mesh, P(None, *spec))
        out = {}
        for k in allowed & set(batch):
            v = np.asarray(batch[k])
            out[k] = jax.device_put(
                v.reshape((m, b // m) + v.shape[1:]), shd)
        return out

    def _resolve_m(self, num_microbatches: Optional[int]) -> int:
        return max(num_microbatches if num_microbatches is not None
                   else self.hpc.chunks, 1)

    def train_step(
        self,
        sp: Params,
        opt: Any,
        batch: Dict[str, np.ndarray],
        num_microbatches: Optional[int] = None,
    ) -> Tuple[Params, Any, Dict[str, Any]]:
        """One fused optimizer step. ``batch`` may be a raw host batch
        ([gbsz, ...] numpy) or the output of :meth:`put_batch` (stacked
        device arrays — zero transfers besides the feed). Metrics stay lazy
        device scalars (no host sync on the step path)."""
        m = self._resolve_m(num_microbatches)
        batch = dict(batch)
        step_rng = batch.pop("dropout_rng", None)
        if self._use_dropout and step_rng is None:
            raise ValueError(
                "cfg enables dropout but the batch has no 'dropout_rng' "
                "key; train_loop/cli add it automatically — manual callers "
                "must pass one per step")
        # .ndim only — np.asarray on a staged device batch would pull the
        # whole token array back to the host every step
        if batch["tokens"].ndim == 2:
            batch = self.put_batch(batch, m)
        if m not in self._step_jits:
            self._step_jits[m] = self._build_step(m, self._use_dropout)
        fn = self._step_jits[m]
        # XLA-counted flops/bytes for the fused program (cost/* gauges;
        # no-op without a metrics sink). BEFORE the call: the step donates
        # (sp, opt, batch), and lowering only reads avals
        maybe_record_jit_cost(
            f"pp/compiled_step_m{m}", fn,
            (sp, opt, batch, step_rng) if self._use_dropout
            else (sp, opt, batch))
        with span("pp/compiled_step"):
            if self._use_dropout:
                out = fn(sp, opt, batch, step_rng)
            else:
                out = fn(sp, opt, batch)
        get_registry().gauge("pp/bubble_frac").set(self.bubble_frac(m))
        return out

    def eval_step(
        self,
        sp: Params,
        batch: Dict[str, np.ndarray],
        num_microbatches: Optional[int] = None,
    ) -> Dict[str, float]:
        """Held-out loss under the training plan (dropout off)."""
        m = self._resolve_m(num_microbatches)
        batch = dict(batch)
        batch.pop("dropout_rng", None)
        if batch["tokens"].ndim == 2:
            batch = self.put_batch(batch, m)
        if m not in self._eval_jits:
            self._eval_jits[m] = self._build_eval(m)
        return {"loss": float(self._eval_jits[m](sp, batch))}

    def compile_count(self) -> int:
        """Total compiled executables across the engine's jit caches — the
        recompile-pinning hook (serving engine convention): steady state
        must hold this constant."""
        return sum(f._cache_size()
                   for f in (*self._step_jits.values(),
                             *self._eval_jits.values()))
