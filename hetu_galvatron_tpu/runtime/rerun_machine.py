"""Fault-detection rerun state machine.

Capability parity with the reference rerun machinery
(runtime/utils/rerun_state_machine.py:127-1307 ``RerunStateMachine`` /
``RerunDataIterator`` / ``RerunErrorInjector``, initialized at
initialize.py:152): validate each step's result (NaN / loss spike), re-run
the same microbatch in place to classify a suspect result as a transient
hardware fault (re-run differs) vs a deterministic/persistent one (re-run
matches), replay batches through a caching iterator, inject synthetic errors
for drills, and signal checkpoint-and-exit with the reference's dedicated
exit codes.

TPU note: determinism is XLA's default on TPU (no atomics-based nondeterminism
like CUDA), which makes the "re-run matches exactly => deterministic issue"
signal stronger than on GPUs.
"""

from __future__ import annotations

import math
import random
from dataclasses import dataclass
from enum import Enum
from typing import Any, Callable, Dict, Iterator, List, Optional

from hetu_galvatron_tpu.core.args_schema import RerunArgs
from hetu_galvatron_tpu.observability.registry import get_registry

# reference exit codes (rerun_state_machine.py:33-37)
EXIT_CODE_RESUME_TO_DISAMBIGUATE = 16
EXIT_CODE_FAILED_ON_RESULT_VALIDATION = 17


class RerunDiagnostic(str, Enum):
    CORRECT = "correct"
    TRANSIENT_ERROR = "transient_error"  # re-run produced a different result
    PERSISTENT_ERROR = "persistent_error"  # re-run reproduced the bad result


class RerunState(str, Enum):
    NOT_RUNNING_YET = "not_running_yet"
    RUNNING = "running"
    RERUNNING_IN_PLACE = "rerunning_in_place"


@dataclass
class RerunRecord:
    iteration: int
    value: float
    rerun_value: Optional[float]
    diagnostic: RerunDiagnostic
    reason: str


class RerunDataIterator:
    """Replayable wrapper: keeps the current step's batches so a rerun
    replays identical data (reference RerunDataIterator,
    rerun_state_machine.py:989)."""

    def __init__(self, it: Iterator):
        self._it = it
        self._cache: List[Any] = []
        self._replaying = False
        self._replay_idx = 0
        # committed (advance()d) batches — the data-iterator position the
        # checkpoint carries for full-state resume
        self.batches_consumed = 0

    def __iter__(self):
        return self

    def __next__(self):
        if self._replaying:
            if self._replay_idx >= len(self._cache):
                raise StopIteration
            item = self._cache[self._replay_idx]
            self._replay_idx += 1
            return item
        item = next(self._it)
        self._cache.append(item)
        return item

    def rewind(self) -> None:
        self._replaying = True
        self._replay_idx = 0

    def advance(self) -> None:
        """Commit the step: drop cached batches, resume the live stream."""
        self.batches_consumed += len(self._cache)
        self._cache.clear()
        self._replaying = False
        self._replay_idx = 0


class InjectedCrash(RuntimeError):
    """Raised by a crash drill (FaultDrill kind="crash"): simulates a
    hard host failure so the supervisor's restart path can be exercised
    end to end."""


class FaultDrill:
    """Deterministic at-step-k fault injection (the configurable half of
    the drill harness; the rate-based :class:`RerunErrorInjector` covers
    stochastic soak tests).

    Driven from ``RerunArgs`` (``inject_kind`` / ``inject_at_iter``):
    ``nan`` and ``spike`` corrupt the step's loss so the rerun machine's
    detection path fires; ``crash`` raises :class:`InjectedCrash`;
    ``preempt`` delivers a real SIGTERM to the process (the supervisor's
    PreemptionGuard must catch it). Each drill fires once, on fresh runs
    only — a resumed run trains clean, which is exactly the
    transient-fault scenario the restart supervisor exists to absorb.
    Every injection is counted (``faults/injected{kind=...}``)."""

    def __init__(self, args: RerunArgs, registry=None):
        self.kind = args.inject_kind
        self.at_iter = args.inject_at_iter
        self.spike_scale = args.inject_spike_scale
        self._registry = registry
        self._armed = self.kind != "none" and self.at_iter >= 0

    def arm(self, start_iter: int) -> None:
        """Disarm on resumed runs (``start_iter > 0``): the drill models a
        one-shot transient fault, not one that reproduces every restart."""
        if start_iter > 0:
            self._armed = False

    @property
    def registry(self):
        return self._registry if self._registry is not None else get_registry()

    def apply(self, value: float, iteration: int) -> float:
        """Corrupt (or crash/preempt on) iteration ``at_iter``; identity
        everywhere else."""
        if not self._armed or iteration != self.at_iter:
            return value
        self._armed = False
        self.registry.counter("faults/injected", kind=self.kind).inc()
        if self.kind == "nan":
            return float("nan")
        if self.kind == "spike":
            return abs(value) * self.spike_scale + 1.0
        if self.kind == "crash":
            raise InjectedCrash(
                f"fault drill: injected crash at iteration {iteration}")
        if self.kind == "preempt":
            import signal

            # a REAL SIGTERM, not a flag poke: the drill exercises the
            # whole preemption path (handler -> boundary stop ->
            # checkpoint -> exit code)
            signal.raise_signal(signal.SIGTERM)
        return value


class RerunErrorInjector:
    """Synthetic fault injection for drills (reference RerunErrorInjector,
    rerun_state_machine.py:1143)."""

    def __init__(self, rate: float = 0.0,
                 kind: str = "transient_error", seed: int = 0):
        self.rate = rate
        self.kind = kind
        self._rng = random.Random(seed)
        self._injected_iters: Dict[int, int] = {}

    def maybe_corrupt(self, value: float, iteration: int,
                      attempt: int) -> float:
        if self.rate <= 0:
            return value
        if attempt == 0:
            if self._rng.random() < self.rate:
                self._injected_iters[iteration] = 1
                return float("nan")
            return value
        # rerun attempt: persistent faults reproduce, transient ones vanish
        if iteration in self._injected_iters and \
                self.kind == "persistent_error":
            return float("nan")
        return value


@dataclass
class DeterminismStats:
    """Relative-difference stats between a step and its in-place re-run
    (reference QuickStats, rerun_state_machine.py:235/520-539)."""

    checked: int = 0
    mismatches: int = 0
    nonfinite: int = 0  # one-sided NaN/inf re-runs (counted, not averaged)
    max_rel_diff: float = 0.0
    sum_rel_diff: float = 0.0

    def record(self, a: float, b: float) -> None:
        self.checked += 1
        denom = max(abs(b), 1e-12)
        rel = abs(a - b) / denom
        if not math.isfinite(rel):
            # one side non-finite: a mismatch by definition, but keep the
            # running mean finite
            self.mismatches += 1
            self.nonfinite += 1
            return
        if rel > 0:
            self.mismatches += 1
        self.max_rel_diff = max(self.max_rel_diff, rel)
        self.sum_rel_diff += rel

    def summary(self) -> Dict[str, Any]:
        finite = self.checked - self.nonfinite
        return {
            "checked": self.checked,
            "mismatches": self.mismatches,
            "nonfinite": self.nonfinite,
            "max_rel_diff": self.max_rel_diff,
            "mean_rel_diff": (self.sum_rel_diff / finite if finite else 0.0),
        }


class RerunStateMachine:
    """Wraps the host train loop's step result (reference
    should_run_forward_backward :251 / validate_result :434)."""

    def __init__(self, args: RerunArgs, registry=None):
        self.args = args
        self.state = RerunState.NOT_RUNNING_YET
        self.records: List[RerunRecord] = []
        self.injector = RerunErrorInjector(
            args.error_injection_rate, args.error_injection_type)
        self._ema: Optional[float] = None
        self._last_exit_code: Optional[int] = None
        self.determinism_stats = DeterminismStats()
        # state transitions double as observability counters (rerun/*), so
        # a fleet dashboard sees fault attribution without parsing logs.
        # None late-binds the process default at increment time (the train
        # launcher may configure sinks after constructing this machine)
        self._registry = registry

    @property
    def registry(self):
        return self._registry if self._registry is not None else get_registry()

    def _count(self, name: str, **labels) -> None:
        self.registry.counter(f"rerun/{name}", **labels).inc()

    @property
    def enabled(self) -> bool:
        return self.args.enable and self.args.mode != "disabled"

    # -- validation ---------------------------------------------------------

    def _suspicious(self, value: float) -> Optional[str]:
        if self.args.check_for_nan and (math.isnan(value)
                                        or math.isinf(value)):
            return "non-finite loss"
        if self.args.check_for_spike and self._ema is not None and \
                value > self.args.spike_factor * self._ema:
            return (f"loss spike: {value:.4f} > {self.args.spike_factor} x "
                    f"EMA {self._ema:.4f}")
        return None

    def _update_ema(self, value: float) -> None:
        if math.isfinite(value):
            self._ema = (value if self._ema is None
                         else 0.9 * self._ema + 0.1 * value)

    def validate_result(
        self,
        value: float,
        iteration: int,
        rerun_fn: Optional[Callable[[], float]] = None,
        data_iterator: Optional[RerunDataIterator] = None,
    ) -> RerunDiagnostic:
        """Check one step's loss; on suspicion re-run the identical step to
        attribute the fault. Returns the diagnostic; exit-code requests are
        exposed via :meth:`exit_code_requested`."""
        if not self.enabled:
            self._update_ema(value)
            return RerunDiagnostic.CORRECT
        value = self.injector.maybe_corrupt(value, iteration, attempt=0)
        self.state = RerunState.RUNNING
        self._count("validated")

        if self.args.mode == "report_stats":
            # determinism-stats mode (reference REPORT_DETERMINISM_STATS,
            # rerun_state_machine.py:77/327/520-539): EVERY step re-runs once
            # and the relative difference is recorded; execution always
            # continues and no exit codes are raised. On TPU/XLA the expected
            # difference is exactly 0 — any nonzero entry is a finding.
            if rerun_fn is not None:
                self.state = RerunState.RERUNNING_IN_PLACE
                self._count("rerun_in_place")
                if data_iterator is not None:
                    data_iterator.rewind()
                # injector applies to the re-run too (attempt=1), matching
                # the validate_results path — a persistent-fault drill must
                # reproduce on the re-run, not read as nondeterminism
                rerun_value = self.injector.maybe_corrupt(
                    float(rerun_fn()), iteration, attempt=1)
                # NaN == NaN for determinism purposes (same guard as the
                # validate_results path): a deterministic NaN step is not a
                # mismatch and must not poison the stats with nan rel-diffs
                same = (rerun_value == value) or (
                    math.isnan(rerun_value) and math.isnan(value))
                if not (math.isnan(rerun_value) and math.isnan(value)):
                    self.determinism_stats.record(rerun_value, value)
                if not same:
                    self._count("determinism_mismatch")
                    self.records.append(RerunRecord(
                        iteration=iteration, value=value,
                        rerun_value=rerun_value,
                        diagnostic=RerunDiagnostic.TRANSIENT_ERROR,
                        reason="nondeterministic re-run"))
                self.state = RerunState.RUNNING
            self._update_ema(value)
            return RerunDiagnostic.CORRECT

        reason = self._suspicious(value)
        if reason is None:
            self._update_ema(value)
            return RerunDiagnostic.CORRECT

        self._count("suspect")
        self.registry.counter(
            "faults/detected",
            kind="nan" if "non-finite" in reason else "spike").inc()
        diagnostic = RerunDiagnostic.PERSISTENT_ERROR
        rerun_value: Optional[float] = None
        if rerun_fn is not None:
            self.state = RerunState.RERUNNING_IN_PLACE
            self._count("rerun_in_place")
            if data_iterator is not None:
                data_iterator.rewind()
            rerun_value = self.injector.maybe_corrupt(
                float(rerun_fn()), iteration, attempt=1)
            same = (rerun_value == value) or (
                math.isnan(rerun_value) and math.isnan(value))
            diagnostic = (RerunDiagnostic.PERSISTENT_ERROR if same
                          else RerunDiagnostic.TRANSIENT_ERROR)
        self.records.append(RerunRecord(
            iteration=iteration, value=value, rerun_value=rerun_value,
            diagnostic=diagnostic, reason=reason))
        self._count(diagnostic.value)  # transient_error / persistent_error
        self.state = RerunState.RUNNING
        if self.args.mode == "validate_results":
            self._last_exit_code = (
                EXIT_CODE_FAILED_ON_RESULT_VALIDATION
                if diagnostic == RerunDiagnostic.PERSISTENT_ERROR
                else EXIT_CODE_RESUME_TO_DISAMBIGUATE)
            self._count("exit_requested", code=self._last_exit_code)
        return diagnostic

    def exit_code_requested(self) -> Optional[int]:
        """Non-None when the run should checkpoint and exit with the given
        code (reference exit codes 16/17)."""
        return self._last_exit_code

    # -- full-state resume --------------------------------------------------

    @staticmethod
    def _enc(v: Optional[float]) -> Any:
        """Strict-JSON-safe float: NaN/inf become strings (json.dump's
        default emits bare ``NaN`` tokens no spec-compliant parser — jq,
        other languages — accepts, and fault records contain NaN by
        construction)."""
        if v is not None and not math.isfinite(v):
            return str(v)  # "nan" / "inf" / "-inf"
        return v

    @staticmethod
    def _dec(v: Any) -> Optional[float]:
        return float(v) if isinstance(v, str) else v

    def state_dict(self) -> Dict[str, Any]:
        """Strict-JSON-serializable snapshot carried in the checkpoint's
        train_state: a resumed run keeps the fault history (and the spike
        EMA, so detection thresholds do not reset to cold)."""
        return {
            "records": [
                {"iteration": r.iteration, "value": self._enc(r.value),
                 "rerun_value": self._enc(r.rerun_value),
                 "diagnostic": r.diagnostic.value, "reason": r.reason}
                for r in self.records
            ],
            "ema": self._enc(self._ema),
            "injected_iters": dict(self.injector._injected_iters),
        }

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        self.records = [
            RerunRecord(
                iteration=r["iteration"], value=self._dec(r["value"]),
                rerun_value=self._dec(r.get("rerun_value")),
                diagnostic=RerunDiagnostic(r["diagnostic"]),
                reason=r.get("reason", ""))
            for r in state.get("records", [])
        ]
        self._ema = self._dec(state.get("ema"))
        self.injector._injected_iters = {
            int(k): v for k, v in state.get("injected_iters", {}).items()}

    def report(self) -> Dict[str, Any]:
        out = {
            "checked_iterations": len(self.records),
            "transient": sum(r.diagnostic == RerunDiagnostic.TRANSIENT_ERROR
                             for r in self.records),
            "persistent": sum(r.diagnostic == RerunDiagnostic.PERSISTENT_ERROR
                              for r in self.records),
            "records": [r.__dict__ for r in self.records],
        }
        if self.args.mode == "report_stats":
            out["determinism"] = self.determinism_stats.summary()
        return out
