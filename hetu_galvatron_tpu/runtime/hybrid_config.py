"""Hybrid-parallel configuration: GLOBAL/JSON modes -> per-layer strategies.

Capability parity with the reference's config expansion
(runtime/hybrid_parallel_config.py:18-130 ``get_hybrid_parallel_configs_api``,
:229-369 ``hp_config_whole_model`` + ``get_chunks``): GLOBAL mode replicates
the uniform CLI knobs across all layers; JSON mode loads a searched
``galvatron_config_*.json`` plan and overrides global_bsz / chunks / pp_deg /
vocab degrees from it; the whole-model expansion attaches vocab-strategy rows
for the embedding / final-norm / LM-head; ``chunks == -1`` auto-computes the
microbatch count.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional

import math

from hetu_galvatron_tpu.analysis import eligibility
from hetu_galvatron_tpu.core.args_schema import CoreArgs
from hetu_galvatron_tpu.utils.strategy import (
    DPType,
    EmbeddingLMHeadStrategy,
    LayerStrategy,
    config2strategy,
    default_pp_division,
    load_strategy_config,
)


@dataclass
class HybridParallelConfig:
    """Resolved plan for the whole model (the reference's
    hybrid_parallel_configs dict, hybrid_parallel_config.py:120-139)."""

    layers: List[LayerStrategy]  # one per transformer layer (see note below)
    vocab: EmbeddingLMHeadStrategy
    pp_deg: int
    pp_division: List[int]  # layers per stage, sums to len(layers)
    chunks: int
    global_bsz: int
    pipeline_type: str
    default_dp_type: DPType
    world_size: int
    # Encoder-decoder models (t5): ``layers`` spans the COMBINED stack —
    # encoder layers first, then decoder layers — and this records the split
    # point. 0 for decoder-only models. ``pp_division`` likewise divides the
    # combined stack, so a stage may hold encoder layers, decoder layers, or
    # the enc->dec boundary.
    num_encoder_layers: int = 0
    # Dataloader-side zigzag cp layout (reference get_batch zigzag slice,
    # utils.py:295): sequences arrive pre-permuted; ring layers skip the
    # in-layer layout reshard. Only set with a uniform cp > 1.
    cp_zigzag: bool = False
    # Interleaved virtual stages (beyond the reference): pp_division has
    # pp_deg * vpp_deg entries; chunk c runs on physical group c % pp_deg.
    vpp_deg: int = 1
    # Searched plans carry the cost model's per-layer compute prediction
    # (fct+bct, ms) so the plan audit can diff the exact model that picked
    # the plan; None for GLOBAL-mode or pre-audit plan files.
    predicted_layer_compute_ms: Optional[List[float]] = None
    # The search priced this plan's dp gradient reduction hierarchically
    # ("hier_dp": 1 in the plan JSON) — the launcher enables the matching
    # runtime path (ops/hier_reduce.py; args.parallel.hier_dp ORs in).
    hier_dp: bool = False
    # Bucketed software-pipelining granularity the search priced it at
    # ("hier_bucket_mb" in the plan JSON; 0 = monolithic). The runtime
    # buckets at the same size; a nonzero parallel.hier_bucket_mb wins.
    hier_bucket_mb: float = 0.0
    # Synthesized collective schedule family the search priced the dp
    # reduction with ("dp_schedule" in the plan JSON; collectives/); None
    # runs the hand-implemented three-stage hierarchical path.
    dp_schedule: Optional[str] = None

    @property
    def enc_strategies(self) -> List[LayerStrategy]:
        return self.layers[:self.num_encoder_layers]

    @property
    def dec_strategies(self) -> List[LayerStrategy]:
        return self.layers[self.num_encoder_layers:]

    @property
    def pp_stage_of_layer(self) -> List[int]:
        """Layer index -> pipeline stage (reference pp_ranks_enc)."""
        out = []
        for stage, n in enumerate(self.pp_division):
            out.extend([stage] * n)
        return out

    def describe(self) -> str:
        from hetu_galvatron_tpu.utils.strategy import print_strategies

        return (f"pp{self.pp_deg} chunks{self.chunks} bsz{self.global_bsz} "
                f"[{print_strategies(self.layers)}] vocab(vtp{self.vocab.vtp}"
                f"{' vsp' if self.vocab.vsp else ''})")


def resolve_chunks(chunks: int, pp_deg: int, global_bsz: int,
                   world_size: int) -> int:
    """Shared chunks resolution for GLOBAL and JSON paths (reference
    get_chunks, hybrid_parallel_config.py:359-368): only -1 auto-computes
    (aiming for microbatches of ~4 samples per max-dp rank); 0 clamps to 1."""
    if chunks != -1:
        return max(chunks, 1)
    if pp_deg <= 1:
        return 1
    max_dp = world_size // pp_deg
    local_bsz = global_bsz / max(max_dp, 1)
    return max(int(math.ceil(local_bsz / 4)), 1)


def get_chunks(args: CoreArgs, world_size: int) -> int:
    return resolve_chunks(args.parallel.chunks, args.parallel.pp_deg,
                          args.parallel.global_train_batch_size, world_size)


def get_hybrid_parallel_config(
    args: CoreArgs, world_size: int
) -> HybridParallelConfig:
    """GLOBAL or JSON mode -> HybridParallelConfig (reference
    get_hybrid_parallel_configs_api, hybrid_parallel_config.py:18-130)."""
    par = args.parallel
    n_enc = 0
    if args.model.model_type == "t5":
        n_enc = (args.model.num_encoder_layers
                 if args.model.num_encoder_layers is not None
                 else args.model.num_hidden_layers)
    n_layers = args.model.num_hidden_layers + n_enc
    use_json = par.config_mode == "json" or (
        par.galvatron_config_path not in (None, "", "None"))

    if use_json:
        cfg = load_strategy_config(par.galvatron_config_path)
        layers, vocab, extras = config2strategy(cfg, world_size=world_size)
        if len(layers) != n_layers:
            raise ValueError(
                f"plan has {len(layers)} layers, model has {n_layers} "
                f"(encoder {n_enc} + decoder "
                f"{args.model.num_hidden_layers})")
        if extras["num_encoder_layers"] not in (None, n_enc):
            raise ValueError(
                f"plan was searched for {extras['num_encoder_layers']} "
                f"encoder layers, model has {n_enc}")
        pp_deg = layers[0].pp_deg
        global_bsz = extras["global_bsz"] or par.global_train_batch_size
        chunks = resolve_chunks(extras["chunks"], pp_deg, global_bsz,
                                world_size)
        pipeline_type = extras["pipeline_type"]
        default_dp = DPType.from_name(extras["default_dp_type"])
        vpp = max(extras.get("vpp_deg", 1), 1)
        pp_division = extras["pp_division"] or default_pp_division(
            n_layers, pp_deg * vpp)
        pred_layer_ms = extras.get("predicted_layer_compute_ms")
        hier_dp = bool(extras.get("hier_dp", False))
        hier_bucket_mb = float(extras.get("hier_bucket_mb", 0.0) or 0.0)
        dp_schedule = extras.get("dp_schedule") or None
    else:
        pp_deg = par.pp_deg
        r = eligibility.pp_world_reason(world_size, pp_deg)
        if r:
            raise ValueError(r)
        stage = world_size // pp_deg
        tp = max(par.global_tp_deg, 1)
        cp = max(par.global_cp_deg, 1)
        r = eligibility.stage_degree_reason(world_size, pp_deg, tp, cp)
        if r:
            raise ValueError(r)
        default_dp = DPType.from_name(par.default_dp_type)
        dp_type = DPType.ZERO3 if par.sdp else default_dp
        base = LayerStrategy(
            pp_deg=pp_deg, tp_size=tp, cp_size=cp, dp_size=stage // (tp * cp),
            sp=par.use_ulysses, tp_consecutive=bool(par.global_tp_consec),
            dp_type=dp_type, checkpoint=bool(par.global_checkpoint),
            ep_size=max(par.global_ep_deg, 1),
            etp_size=max(par.global_etp_deg, 1),
        )
        layers = [base] * n_layers
        vocab = EmbeddingLMHeadStrategy(
            vtp=par.vocab_tp,
            vsp=bool(par.vocab_sp) or par.use_ulysses,  # ulysses forces vsp
            vcp=par.vocab_cp,
            embed_sdp=bool(par.embed_sdp),
        )
        global_bsz = par.global_train_batch_size
        pipeline_type = par.pipeline_type
        vpp = max(par.virtual_pp_deg, 1)
        pp_division = default_pp_division(n_layers, pp_deg * vpp)
        chunks = get_chunks(args, world_size)
        pred_layer_ms = None
        hier_dp = False
        hier_bucket_mb = 0.0
        dp_schedule = None

    # guard both branches (a JSON plan with pp*vpp > layers would otherwise
    # slip through as zero-layer chunks from default_pp_division): the
    # structural predicates are shared with the plan doctor, which reports
    # ALL of them instead of raising on the first
    for reason in (
            eligibility.vpp_layers_reason(pp_deg, vpp, n_layers),
            eligibility.pp_division_sum_reason(pp_division, n_layers),
            eligibility.pp_division_len_reason(pp_division, pp_deg, vpp),
            eligibility.batch_grain_reason(global_bsz, world_size, pp_deg,
                                           layers, vocab)):
        if reason is not None:
            raise ValueError(reason)
    cp_zigzag = bool(getattr(args.parallel, "cp_zigzag", False))
    if cp_zigzag:
        cps = {s.cp_size for s in layers}
        if len(cps) != 1:
            # a non-ring layer would causally mask PERMUTED data by its
            # array order — silently wrong; demand an all-ring stack
            raise ValueError(
                "parallel.cp_zigzag needs a UNIFORM cp degree across all "
                f"layers (plan has {sorted(cps)}): pre-permuted sequences "
                "are only correct when every attention layer is zigzag "
                "ring")
        if cps == {1}:
            cp_zigzag = False  # no cp: the flag is a no-op
        elif args.model.model_type in ("bert", "t5"):
            raise ValueError(
                "parallel.cp_zigzag is a causal-LM data layout "
                "(bert/t5 batches are not zigzag-slicable)")
    return HybridParallelConfig(
        layers=list(layers), vocab=vocab, pp_deg=pp_deg,
        pp_division=list(pp_division), chunks=chunks, global_bsz=global_bsz,
        pipeline_type=pipeline_type, default_dp_type=default_dp,
        world_size=world_size, num_encoder_layers=n_enc, vpp_deg=vpp,
        cp_zigzag=cp_zigzag, predicted_layer_compute_ms=pred_layer_ms,
        hier_dp=hier_dp, hier_bucket_mb=hier_bucket_mb,
        dp_schedule=dp_schedule,
    )
