"""Microbatch / global-batch-size ramp calculator.

Capability parity with the reference's num-microbatches calculators
(core/runtime/optimizer/num_microbatches_calculator.py:16-508:
``ConstantNumMicroBatchesCalculator`` /
``RampupBatchsizeNumMicroBatchesCalculator`` behind module-level getters):
the global batch size ramps from ``start`` to the target in fixed
``increment`` steps spread evenly over ``ramp_samples`` consumed samples,
and each step's batch is expressed as N microbatches of a FIXED micro size.

TPU note: the fixed micro size is what makes ramping XLA-friendly — every
compiled program (SPMD scan body or pipeline stage jit) sees one static
microbatch shape for the whole run; only the microbatch COUNT varies, so a
whole ramp costs at most one compile per distinct chunk count (SPMD scan)
or zero extra compiles (pipeline engine, which loops stages per microbatch).
"""

from __future__ import annotations

from typing import List, Optional, Sequence


def _round_down(batch_size: int, divisor: int) -> int:
    return (batch_size // divisor) * divisor


class MicroBatchCalculator:
    """Constant or ramped global batch size -> per-iteration microbatching.

    Args:
        global_batch_size: the target (final) global batch size.
        micro_batch_size: samples per microbatch per dp replica group —
            constant for the whole run.
        dp_size: data-parallel replica count (microbatch shape divisor).
        rampup_batch_size: None for constant, else
            ``[start_global_batch_size, increment, ramp_samples]``
            (the reference's --rampup-batch-size triple).
        decrease_batch_size_if_needed: round a ramp step down to
            micro*dp divisibility instead of asserting.
    """

    def __init__(
        self,
        global_batch_size: int,
        micro_batch_size: int,
        dp_size: int = 1,
        rampup_batch_size: Optional[Sequence[int]] = None,
        decrease_batch_size_if_needed: bool = False,
    ):
        if global_batch_size <= 0 or micro_batch_size <= 0 or dp_size <= 0:
            raise ValueError("batch sizes and dp_size must be positive")
        self.global_batch_size = int(global_batch_size)
        self.micro_batch_size = int(micro_batch_size)
        self.dp_size = int(dp_size)
        self.decrease_batch_size_if_needed = bool(decrease_batch_size_if_needed)
        self._micro_times_dp = self.micro_batch_size * self.dp_size

        if rampup_batch_size is None:
            self.start_global_batch_size = self.global_batch_size
            self.batch_size_increment = 0
            self.ramp_samples = 0
            self._samples_per_increment = 0.0
        else:
            if len(rampup_batch_size) != 3:
                raise ValueError(
                    "rampup_batch_size must be [start, increment, "
                    f"ramp_samples], got {rampup_batch_size}")
            start, inc, ramp = (int(v) for v in rampup_batch_size)
            if start <= 0 or inc <= 0 or ramp < 0:
                raise ValueError(
                    f"invalid rampup triple {rampup_batch_size}")
            diff = self.global_batch_size - start
            if diff < 0:
                raise ValueError(
                    f"start batch size {start} exceeds target "
                    f"{self.global_batch_size}")
            if diff % inc:
                raise ValueError(
                    f"batch size span {diff} not divisible by increment {inc}")
            self.start_global_batch_size = start
            self.batch_size_increment = inc
            self.ramp_samples = ramp
            num_increments = max(diff // inc, 1)
            # ramp_samples=0 = jump straight to the target batch size
            self._samples_per_increment = (ramp / num_increments
                                           if ramp > 0 else 0.0)

        self.current_global_batch_size = 0
        self.current_running_global_batch_size = 0
        self.num_micro_batches = 0
        self.update(0)

    # -- reference getter surface ------------------------------------------

    def get(self) -> int:
        """Number of microbatches at the current point in the ramp."""
        return self.num_micro_batches

    def get_current_global_batch_size(self) -> int:
        return self.current_global_batch_size

    def get_current_running_global_batch_size(self) -> int:
        return self.current_running_global_batch_size

    def get_micro_batch_size(self) -> int:
        return self.micro_batch_size

    @property
    def is_ramping(self) -> bool:
        return self.batch_size_increment > 0

    # -- schedule ----------------------------------------------------------

    def update(self, consumed_samples: int) -> bool:
        """Recompute the current batch size from total consumed samples
        (reference update(), num_microbatches_calculator.py:442-508).
        Returns True when the global batch size changed."""
        old = self.current_global_batch_size
        if (not self.is_ramping or self._samples_per_increment == 0
                or consumed_samples > self.ramp_samples):
            cur = self.global_batch_size
        else:
            steps = int(consumed_samples / self._samples_per_increment)
            cur = min(self.start_global_batch_size
                      + steps * self.batch_size_increment,
                      self.global_batch_size)
        self.current_global_batch_size = cur

        if cur % self._micro_times_dp:
            if not self.decrease_batch_size_if_needed:
                raise ValueError(
                    f"global batch size {cur} is not divisible by "
                    f"micro_batch_size {self.micro_batch_size} * dp_size "
                    f"{self.dp_size}")
            running = max(_round_down(cur, self._micro_times_dp),
                          self._micro_times_dp)
        else:
            running = cur
        self.current_running_global_batch_size = running
        self.num_micro_batches = running // self._micro_times_dp
        return cur != old

    def schedule(self, total_samples: int) -> List[int]:
        """The full ramp as a list of per-iteration global batch sizes until
        ``total_samples`` are consumed — handy for tests and logging."""
        out: List[int] = []
        consumed = 0
        while consumed < total_samples:
            self.update(consumed)
            out.append(self.current_running_global_batch_size)
            consumed += self.current_running_global_batch_size
        self.update(0)
        return out


class Rebatcher:
    """Re-slice a fixed-size batch stream into ramped batch sizes.

    The data iterators yield dict batches of the TARGET global size; during
    a ramp the runtime consumes smaller batches. This wrapper buffers
    samples (row-wise) and emits exactly-``n``-sample batches, preserving
    sample order — the reference achieves the same by driving its sampler
    with consumed_samples directly (dataloader.py:83-120)."""

    def __init__(self, it):
        self._it = it
        self._buf = None

    def next_batch(self, n: int):
        import numpy as np

        while self._buf is None or len(next(iter(self._buf.values()))) < n:
            batch = next(self._it)
            if self._buf is None:
                self._buf = {k: np.asarray(v) for k, v in batch.items()}
            else:
                self._buf = {k: np.concatenate([self._buf[k], batch[k]])
                             for k in self._buf}
        out = {k: v[:n] for k, v in self._buf.items()}
        self._buf = {k: v[n:] for k, v in self._buf.items()}
        return out
