from hetu_galvatron_tpu.runtime.hybrid_config import (  # noqa: F401
    HybridParallelConfig,
    get_chunks,
    get_hybrid_parallel_config,
)
from hetu_galvatron_tpu.runtime.mesh import (  # noqa: F401
    LayerSharding,
    build_mesh,
    lower_strategy,
    lower_vocab_strategy,
    stage_axes,
)
