"""Lightweight in-process metrics registry.

Counters, gauges, and histograms with labels, snapshot-flushed to pluggable
sinks (``sinks.py``). Design constraints, in order:

1. **Hot-path cost is a dict lookup + a float op.** Metric handles are
   cached per (name, labels) so instrumented code can call
   ``registry.counter("x").inc()`` every iteration; nothing touches a sink
   until ``flush()``.
2. **Safe as a process-wide default.** Instrumentation inside library code
   (rerun machine, profiler, pipeline) goes through :func:`get_registry`,
   which always returns a live registry — with no sinks attached it is a
   pure in-memory accumulator, so un-configured runs pay only the float op.
3. **Bounded memory.** Histograms keep a capped sample buffer (random-ish
   decimation beyond the cap) so million-step runs cannot OOM the host.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Tuple

import numpy as np

LabelKey = Tuple[Tuple[str, str], ...]


def _label_key(labels: Dict[str, Any]) -> LabelKey:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class Counter:
    """Monotonically increasing value."""

    kind = "counter"

    def __init__(self, name: str, labels: Dict[str, str]):
        self.name = name
        self.labels = labels
        self.value = 0.0

    def inc(self, n: float = 1.0) -> None:
        self.value += n

    def snapshot(self) -> Dict[str, Any]:
        return {"value": self.value}


class Gauge:
    """Last-write-wins value."""

    kind = "gauge"

    def __init__(self, name: str, labels: Dict[str, str]):
        self.name = name
        self.labels = labels
        self.value = 0.0

    def set(self, v: float) -> None:
        self.value = float(v)

    def snapshot(self) -> Dict[str, Any]:
        return {"value": self.value}


class Histogram:
    """Sample distribution with percentile snapshots.

    Keeps at most ``cap`` samples: past the cap, every other retained
    sample is dropped and the keep-stride doubles, preserving an unbiased
    spread over the whole run at O(cap) memory. count/sum stay exact.
    """

    kind = "histogram"

    def __init__(self, name: str, labels: Dict[str, str], cap: int = 4096):
        self.name = name
        self.labels = labels
        self.cap = cap
        self.count = 0
        self.total = 0.0
        self._samples: List[float] = []
        self._stride = 1

    def observe(self, v: float) -> None:
        v = float(v)
        self.count += 1
        self.total += v
        if self.count % self._stride == 0:
            self._samples.append(v)
            if len(self._samples) >= self.cap:
                self._samples = self._samples[::2]
                self._stride *= 2

    def percentile(self, q: float) -> float:
        if not self._samples:
            return 0.0
        return float(np.percentile(np.asarray(self._samples), q))

    def snapshot(self) -> Dict[str, Any]:
        if not self.count:
            return {"count": 0, "mean": 0.0, "min": 0.0, "max": 0.0,
                    "p50": 0.0, "p90": 0.0, "p99": 0.0}
        arr = np.asarray(self._samples)
        p50, p90, p99 = np.percentile(arr, [50, 90, 99])
        return {
            "count": self.count,
            "mean": self.total / self.count,
            "min": float(arr.min()),
            "max": float(arr.max()),
            "p50": float(p50),
            "p90": float(p90),
            "p99": float(p99),
        }


class MetricsRegistry:
    """Holds metric instances and routes snapshots/events to sinks."""

    def __init__(self, sinks: Iterable[Any] = ()):
        self._sinks: List[Any] = list(sinks)
        self._metrics: Dict[Tuple[str, str, LabelKey], Any] = {}
        self._lock = threading.Lock()

    # -- sinks --------------------------------------------------------------

    def add_sink(self, sink: Any) -> None:
        self._sinks.append(sink)

    @property
    def sinks(self) -> List[Any]:
        return list(self._sinks)

    # -- metric handles -----------------------------------------------------

    def _get(self, cls, name: str, labels: Dict[str, Any]):
        key = (cls.kind, name, _label_key(labels))
        m = self._metrics.get(key)
        if m is None:
            with self._lock:
                m = self._metrics.get(key)
                if m is None:
                    m = cls(name, {str(k): str(v)
                                   for k, v in labels.items()})
                    self._metrics[key] = m
        return m

    def counter(self, name: str, **labels) -> Counter:
        return self._get(Counter, name, labels)

    def gauge(self, name: str, **labels) -> Gauge:
        return self._get(Gauge, name, labels)

    def histogram(self, name: str, **labels) -> Histogram:
        return self._get(Histogram, name, labels)

    def metrics(self) -> List[Any]:
        return list(self._metrics.values())

    # -- output -------------------------------------------------------------

    def event(self, name: str, data: Optional[Dict[str, Any]] = None,
              step: Optional[int] = None) -> None:
        """One-off structured record, written through immediately (sinks
        buffer internally) — used for span records and search-trace
        entries."""
        rec = {"t": time.time(), "kind": "event", "name": name,
               "data": data or {}}
        if step is not None:
            rec["step"] = step
        for s in self._sinks:
            s.write(rec)

    def flush(self, step: Optional[int] = None) -> None:
        """Snapshot every metric into each sink, then flush the sinks."""
        now = time.time()
        for m in self.metrics():
            rec = {"t": now, "kind": m.kind, "name": m.name, "step": step}
            if m.labels:
                rec["labels"] = m.labels
            rec.update(m.snapshot())
            for s in self._sinks:
                s.write(rec)
        for s in self._sinks:
            s.flush()

    def close(self, step: Optional[int] = None) -> None:
        self.flush(step)
        for s in self._sinks:
            s.close()


# ---------------------------------------------------------------------------
# process-wide default
# ---------------------------------------------------------------------------

_DEFAULT = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    """The process-wide registry library instrumentation uses. Always live;
    with no sinks configured it is a free-standing accumulator."""
    return _DEFAULT


def set_registry(reg: MetricsRegistry) -> MetricsRegistry:
    global _DEFAULT
    _DEFAULT = reg
    return reg


def configure(jsonl_path: Optional[str] = None,
              tensorboard_dir: Optional[str] = None) -> MetricsRegistry:
    """Install a fresh default registry with the requested sinks attached.
    The TensorBoard sink silently degrades to absent when no writer library
    is importable (or ``HGTPU_NO_TENSORBOARD`` is set)."""
    from hetu_galvatron_tpu.observability.sinks import (
        JsonlSink,
        make_tensorboard_sink,
    )

    sinks: List[Any] = []
    if jsonl_path:
        sinks.append(JsonlSink(jsonl_path))
    if tensorboard_dir:
        tb = make_tensorboard_sink(tensorboard_dir)
        if tb is not None:
            sinks.append(tb)
    return set_registry(MetricsRegistry(sinks))
