"""Derived training telemetry: throughput, MFU, memory, plan comm volume.

:class:`TrainingTelemetry` is a ``train_loop`` hook (``hook(it, metrics)``)
that turns raw step metrics into the numbers the ROADMAP cares about:

* ``train/step_time_ms`` histogram — host wall-clock between hook calls.
  Under async dispatch the host runs ahead of the device until XLA's
  in-flight limit back-pressures it, so after a couple of warmup steps the
  host cadence equals device step time without ever calling
  ``block_until_ready``.
* ``train/tokens_per_sec`` gauge — windowed tokens/s.
* ``train/mfu`` gauge — model-FLOPs utilization: achieved model FLOP/s
  (tokens/s x analytic FLOPs/token from ``core/cost_model/cost.py``) over
  the device fleet's peak FLOP/s (:func:`peak_device_tflops`, overridable
  for hardware the table does not know).
* ``train/loss`` / ``train/grad_norm`` gauges — device scalars buffered
  un-synced and converted one flush LATE, so the hot loop never blocks on
  an in-flight value (the "no float() in the step loop" contract the CPU
  smoke test pins).
* ``device/mem_mb`` gauges — allocator stats at flush time (host-side API,
  no device sync; absent on backends without allocator stats).

:func:`plan_comm_volume` computes each layer's PREDICTED per-step
collective volume from the strategy plan (mirroring the message-size
arithmetic in ``core/cost_model/cost.py``), emitted once in the one-shot
``plan`` event so a run's observed step time can be audited against what
the search engine thought the plan would communicate ("Revisiting the
Time Cost Model of AllReduce": analytical comm models drift; keep the
receipts).
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Sequence

from hetu_galvatron_tpu.observability.registry import (
    MetricsRegistry,
    get_registry,
)

MB = 1024 * 1024

# bf16 peak TFLOP/s per chip by device_kind substring (generation specs;
# matched case-insensitively against jax device_kind strings like
# "TPU v5 lite"). CPUs and unknown kinds resolve to None — MFU is then
# emitted only when the caller supplies peak_tflops_per_device.
_PEAK_TFLOPS = (
    ("v5 lite", 197.0), ("v5litepod", 197.0), ("v5e", 197.0),
    ("v6 lite", 918.0), ("v6e", 918.0),
    ("v5p", 459.0),
    ("v4", 275.0),
    ("v3", 123.0),
    ("v2", 45.0),
)


def peak_device_tflops(device_kind: str) -> Optional[float]:
    """Per-chip bf16 peak for a jax ``device_kind`` string, or None when
    unknown (CPU, new hardware)."""
    kind = (device_kind or "").lower()
    for sub, tf in _PEAK_TFLOPS:
        if sub in kind:
            return tf
    return None


class TrainingTelemetry:
    """Sync-free train-loop hook producing throughput/MFU/memory metrics.

    Call it as ``hook(it, metrics)`` once per step; call :meth:`close`
    (or use as a context manager) at loop exit so the tail of the run is
    flushed. ``metrics`` entries named in ``scalar_keys`` may be live
    device arrays — they are buffered and converted only at the NEXT
    flush boundary, by which point the device finished them long ago.
    """

    def __init__(
        self,
        registry: Optional[MetricsRegistry] = None,
        *,
        model=None,
        global_batch_size: int = 0,
        seq_length: int = 0,
        world_size: int = 1,
        peak_tflops_per_device: float = 0.0,
        flush_interval: int = 16,
        window: int = 32,
        scalar_keys: Sequence[str] = ("loss", "grad_norm"),
    ):
        self.registry = registry if registry is not None else get_registry()
        self.global_batch_size = int(global_batch_size)
        self.seq_length = int(seq_length)
        self.world_size = max(int(world_size), 1)
        self.flush_interval = max(int(flush_interval), 1)
        self.window = max(int(window), 2)
        self.scalar_keys = tuple(scalar_keys)
        self.flops_per_token = 0.0
        if model is not None:
            from hetu_galvatron_tpu.core.cost_model.cost import (
                model_flops_per_token,
            )

            self.flops_per_token = model_flops_per_token(model)
        self.peak_flops = 0.0
        if peak_tflops_per_device > 0:
            self.peak_flops = peak_tflops_per_device * 1e12 * self.world_size
        else:
            kind = _device_kind()
            tf = peak_device_tflops(kind) if kind else None
            if tf:
                self.peak_flops = tf * 1e12 * self.world_size
        self._last_t: Optional[float] = None
        self._times: List[float] = []  # (t, step) ring for the window
        self._steps_seen = 0
        self._pending: List[tuple] = []  # (it, {key: device scalar})
        self._closed = False

    # -- hook ---------------------------------------------------------------

    def __call__(self, it: int, metrics: Dict[str, Any]) -> None:
        now = time.perf_counter()
        self._closed = False  # re-armed: one instance may span many loops
        reg = self.registry
        if self._last_t is not None:
            reg.histogram("train/step_time_ms").observe(
                (now - self._last_t) * 1000.0)
        self._last_t = now
        self._times.append(now)
        if len(self._times) > self.window:
            self._times = self._times[-self.window:]
        self._steps_seen += 1
        reg.counter("train/steps").inc()
        tokens = self.global_batch_size * self.seq_length
        if tokens:
            reg.counter("train/tokens").inc(tokens)
        # buffer device scalars WITHOUT converting — float() here would
        # block async dispatch and serialize host prep with device compute
        pend = {k: metrics[k] for k in self.scalar_keys if k in metrics}
        if pend:
            self._pending.append((it, pend))
        if self._steps_seen % self.flush_interval == 0:
            self.flush(step=it)

    def resume_from(self, step: int, *, samples: Optional[int] = None
                    ) -> None:
        """Carry the telemetry step across a checkpoint resume: the
        cumulative ``train/steps`` / ``train/tokens`` counters restart at
        the checkpointed totals instead of zero, so a preempted-and-resumed
        run's metrics stream is continuous (throughput windows and step
        timing stay process-local — wall-clock did genuinely restart).
        ``samples`` overrides the consumed-sample count for runs whose
        batch size varied (a rampup): ``step * global_batch_size`` would
        overstate the tokens the original run actually trained on."""
        if step <= 0:
            return
        self._steps_seen = int(step)
        self.registry.counter("train/steps").inc(step)
        tokens = (samples * self.seq_length if samples is not None
                  else step * self.global_batch_size * self.seq_length)
        if tokens:
            self.registry.counter("train/tokens").inc(tokens)

    # -- flushing -----------------------------------------------------------

    def _drain_pending(self, final: bool) -> None:
        """Convert buffered device scalars to floats. All but the newest
        entry are at least one step old — the device already finished
        them, so float() returns without stalling; the newest is held
        back until the next flush (or converted at close)."""
        keep = 0 if final else 1
        while len(self._pending) > keep:
            it, vals = self._pending.pop(0)
            for k, v in vals.items():
                self.registry.gauge(f"train/{k}").set(float(v))

    def tokens_per_sec(self) -> float:
        if len(self._times) < 2:
            return 0.0
        span_s = self._times[-1] - self._times[0]
        if span_s <= 0:
            return 0.0
        return (len(self._times) - 1) * self.global_batch_size * \
            self.seq_length / span_s

    def flush(self, step: Optional[int] = None, final: bool = False) -> None:
        reg = self.registry
        self._drain_pending(final)
        tps = self.tokens_per_sec()
        reg.gauge("train/tokens_per_sec").set(tps)
        if self.flops_per_token:
            mflops = tps * self.flops_per_token
            reg.gauge("train/model_tflops").set(mflops / 1e12)
            if self.peak_flops:
                reg.gauge("train/mfu").set(mflops / self.peak_flops)
        self._memory_gauges()
        reg.flush(step)

    def _memory_gauges(self) -> None:
        # lazy import: profiler imports observability, not vice versa
        from hetu_galvatron_tpu.core.profiler.runtime_profiler import (
            device_memory_mb,
        )

        stats = device_memory_mb()
        if stats:
            self.registry.gauge("device/mem_mb", stat="current").set(
                stats["current"])
            self.registry.gauge("device/mem_mb", stat="peak").set(
                stats["peak"])

    def close(self, step: Optional[int] = None) -> None:
        """Final flush (drains ALL buffered device scalars). Idempotent
        until the next ``__call__``, which re-arms the instance — one
        telemetry object may serve several consecutive loops."""
        if self._closed:
            return
        self._closed = True
        self.flush(step, final=True)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def _device_kind() -> str:
    try:
        import jax

        return jax.devices()[0].device_kind
    except Exception:  # jax not initialized / no devices
        return ""


# ---------------------------------------------------------------------------
# predicted per-strategy comm volume (from the plan JSON)
# ---------------------------------------------------------------------------


def layer_param_mb(model) -> float:
    """Per-decoder-layer parameter megabytes at fp32 (the unit
    ``CostContext.parameter_size`` uses)."""
    h = model.hidden_size
    nd = model.num_attention_heads * model.head_dim
    kd = model.kv_heads * model.head_dim
    attn = h * nd + 2 * h * kd + nd * h
    gated = model.hidden_act in ("swiglu", "geglu")
    ffn = (3 if gated else 2) * h * model.ffn_dim
    norms = 2 * h
    return (attn + ffn + norms) * 4 / MB


def plan_comm_volume(
    layers: Sequence[Any],
    model,
    *,
    global_bsz: int,
    chunks: int,
    mixed_precision: bool = True,
) -> List[Dict[str, float]]:
    """Predicted per-step communication megabytes for each layer of a
    strategy plan (``utils.strategy.LayerStrategy`` list, e.g.
    ``hpc.layers``). Mirrors the message-size arithmetic of
    ``cost_model.cost.layer_time_cost`` — dp gradient sync, tp/sp
    activation collectives (x chunks microbatches), cp ring K/V exchange,
    pp activation p2p — so observed runs can be audited against the cost
    model's communication assumptions."""
    seq, h = model.seq_length, model.hidden_size
    param_mb = layer_param_mb(model)
    elem = 2 if mixed_precision else 4
    out = []
    for s in layers:
        dp, cp = s.dp_size, s.cp_size
        # LayerStrategy encodes Ulysses as sp=True with tp_size holding the
        # sequence-parallel degree (utils/strategy.py:53-72)
        ulysses = s.tp_size if s.sp else 1
        tp = 1 if s.sp else s.tp_size
        tp_sp = max(tp, ulysses)
        # ZeRO shard group: dp x sp x cp (SearchStrategy.sdp)
        sdp = max(dp * cp * ulysses, 1)
        lbsz = max(global_bsz // max(chunks, 1) // max(dp, 1), 1)
        # dp gradient sync: ring all-reduce moves 2(d-1)/d of the shard
        grad_mb = param_mb / tp * (0.5 if mixed_precision else 1.0)
        dp_mb = 2 * (sdp - 1) / sdp * grad_mb if sdp > 1 else 0.0
        # tp/sp activation collectives per microbatch (cost.py:147-161:
        # 4 all-to-alls for Ulysses, 6 allgather-equivalents for TP+SP)
        act_mb = lbsz * seq * h * elem / MB
        if tp_sp > 1:
            comm_num = 4 if ulysses > 1 else 6
            if s.checkpoint:
                comm_num = int(comm_num * 1.5)
            tp_mb = act_mb * comm_num * chunks
        else:
            tp_mb = 0.0
        # cp ring: K+V blocks each hop, fwd + bwd(K/V + dK/dV)
        if cp > 1:
            block_mb = lbsz * seq * h / cp * elem / MB
            cp_mb = block_mb * 2 * (cp - 1) * 3 * chunks
        else:
            cp_mb = 0.0
        # pp activation p2p (fwd activation + bwd cotangent)
        pp_mb = (2 * lbsz * seq * h * elem / MB * chunks
                 if s.pp_deg > 1 else 0.0)
        out.append({"dp_allreduce_mb": dp_mb, "tp_collective_mb": tp_mb,
                    "cp_ring_mb": cp_mb, "pp_p2p_mb": pp_mb,
                    "total_mb": dp_mb + tp_mb + cp_mb + pp_mb})
    return out


def _hier_payload_elems_from_plan(hpc, model, *, cross: int
                                  ) -> Tuple[int, int, int]:
    """(local, padded, intra) per-device payload element counts of the
    hierarchical dp reduction for a pp=1 plan — built from THE SAME spec
    arithmetic the runtime reducer uses (``ops.hier_reduce``: eval-shaped
    params, ``grad_reduce_specs``, ``hier_payload_elems``), so the byte
    prediction cannot drift from the traced program."""
    from types import SimpleNamespace

    import jax

    from hetu_galvatron_tpu.models.builder import init_causal_lm
    from hetu_galvatron_tpu.ops.hier_reduce import (
        grad_reduce_specs,
        hier_payload_elems,
    )
    from hetu_galvatron_tpu.runtime.mesh import (
        lower_strategy,
        lower_vocab_strategy,
    )

    if hpc.pp_deg > 1:
        raise ValueError("hier_dp payload prediction models pp=1 plans; "
                         "pp>1 engines pass their stacked payload via "
                         "the engine's reducer")
    # shape-only mesh stand-in: the prediction needs axis NAMES and SIZES
    # (lower_strategy / axes_size are shape arithmetic), never devices —
    # a plan for 8 chips stays predictable on a 1-device analysis host
    stage = hpc.world_size
    k = stage.bit_length() - 1
    if (1 << k) != stage:
        raise ValueError(f"world {stage} is not a power of two")
    mesh = SimpleNamespace(
        axis_names=("pp",) + tuple(f"d{i}" for i in range(k)),
        shape={"pp": 1, **{f"d{i}": 2 for i in range(k)}})
    per_layer = [lower_strategy(s, mesh) for s in hpc.layers]
    vocab = lower_vocab_strategy(hpc.vocab, mesh, hpc.default_dp_type)
    # eval_shape the params (no arrays materialize); the logical-axes tree
    # is static python built during the trace, captured via the closure
    box = {}

    def only_params(k):
        p, a = init_causal_lm(k, model)
        box["axes"] = a
        return p

    params_shapes = jax.eval_shape(only_params, jax.random.key(0))
    axes_tree = box["axes"]
    specs = grad_reduce_specs(axes_tree, per_layer, vocab)
    dp_deg = max(hpc.layers[0].dp_size, 1)
    if cross < 1 or dp_deg % cross:
        raise ValueError(f"cross-slice degree {cross} does not divide the "
                         f"dp degree {dp_deg}")
    intra = dp_deg // cross
    from jax.sharding import PartitionSpec as P

    shape_leaves = [tuple(s.shape)
                    for s in jax.tree_util.tree_leaves(params_shapes)]
    spec_leaves = jax.tree_util.tree_leaves(
        specs, is_leaf=lambda x: isinstance(x, P))
    # the grad specs never mention the dp (lane) axes, so the flat
    # shape-only view prices the per-device leaf sizes exactly
    local, padded = hier_payload_elems(shape_leaves, spec_leaves, mesh,
                                       intra)
    return local, padded, intra


def _dp_schedule_from_plan(name: str, lanes: int, cross: int,
                           bucket_mb: float):
    """Verified :class:`~hetu_galvatron_tpu.collectives.ir.Schedule` the
    runtime reducer would execute for ``dp_schedule=name`` — the shared
    count/byte prediction source. Hand-built reference backends
    (``*_handbuilt``) predict through their emitted twin: the reference
    bodies are pinned bit- and byte-identical to the emitted programs
    (same hop count, same per-hop payload), so one schedule prices
    both."""
    from hetu_galvatron_tpu.analysis.eligibility import (
        dp_schedule_unsupported_reason,
    )
    from hetu_galvatron_tpu.collectives.synthesize import (
        synthesize_dp_schedule,
    )
    from hetu_galvatron_tpu.collectives.verify import verify

    reason = dp_schedule_unsupported_reason(name, lanes, cross, bucket_mb)
    if reason is not None:
        raise ValueError(f"dp schedule unsupported: {reason}")
    fam = {"ring_handbuilt": "ring",
           "tree_handbuilt": "tree_hd"}.get(name, name)
    return verify(synthesize_dp_schedule(fam, lanes, cross))


def plan_collective_counts(
    hpc,
    model,
    *,
    num_microbatches: Optional[int] = None,
    tp_overlap: bool = True,
    hier_dp: bool = False,
    hier_bucket_mb: float = 0.0,
    hier_cross: int = 1,
    dp_schedule: Optional[str] = None,
) -> Dict[str, int]:
    """Predicted EXECUTED explicit-collective counts for the compiled
    single-program 1F1B step — the count-side companion of
    :func:`plan_comm_volume` (which predicts megabytes), consumed by the
    static jaxpr census (``analysis/census.py``).

    Only the EXPLICIT collectives are predicted: the shard_map kernels'
    ``lax.ppermute`` rings. GSPMD-inserted collectives (dp gradient
    all-reduce, ZeRO gathers) appear at partition time, not in the jaxpr.
    Counts are per one traced step program, INCLUDING the masked bubble
    ticks the lockstep schedule executes (T = m + 2(pp-1) ticks): volumes
    in :func:`plan_comm_volume` scale with the m real microbatches, so the
    count-derived tp volume equals the MB prediction times T/m.

    Arithmetic (mirrors ops/overlap.py + runtime/compiled_pipeline.py):
    per decoder-layer slot and tick, the forward unit runs 4 rings (qkv,
    out-proj, fc1 — the gated pair counts as ONE rotation — and fc2); the
    backward unit recomputes the stage forward from its stored input
    (``jax.vjp``) and runs the 4 transposed rings, so 8 rings, plus
    another 4-ring forward recompute under per-layer remat. Each ring is
    ``tp - 1`` ppermute hops. The stage rotations add 2 ppermutes per tick
    (activations forward, cotangents backward).

    ``hier_dp=True`` adds the hierarchical dp gradient reduction's
    explicit collectives (``ops/hier_reduce.py``): the whole grad tree
    flattens into ONE payload per step, split into ``B`` buckets by
    ``hier_bucket_layout`` (``hier_bucket_mb``; B = 1 at the 0 default),
    so exactly B ``reduce_scatter`` (psum_scatter over the host
    sub-axis), B ``all_reduce`` (psum over the slice sub-axis) and B
    ``all_gather`` — independent of the microbatch count (lane
    accumulation is reduction-free in-scan). Bucketed counts need the
    payload size, so ``hier_bucket_mb > 0`` models pp = 1 plans only
    (``hier_cross`` fixes the slice/host split, as in
    :func:`plan_collective_bytes`); pp > 1 engines predict from their
    own reducer's ``bucket_layout``.

    ``dp_schedule`` (with ``hier_dp=True``) predicts the synthesized
    collective-compiler backend instead: the rs/ar/ag triple is replaced
    by ``ppermute_dp`` — one count per exchange step of the verified
    schedule (``collectives.synthesize`` + ``Schedule.n_exchanges``),
    which the census matches under the ``dp_sched`` scope marker.

    Raises ValueError for plan shapes the prediction does not model
    (non-uniform strategies, Ulysses/cp layers — the census still counts
    those programs, there is just no exact-count prediction to pin them
    to).
    """
    s = hpc.layers[0]
    if any(l != s for l in hpc.layers):
        raise ValueError("collective-count prediction needs a uniform "
                         "per-layer strategy (the compiled engine's gate)")
    if (s.sp or s.cp_size > 1) and (
            not hier_dp or tp_overlap or max(hpc.pp_deg, 1) > 1):
        # the flat path's cp-ring / ulysses-a2a kernel hops have no exact
        # prediction; the hier LANE path swaps those kernels for GSPMD
        # (partition-time, invisible to the jaxpr), so its explicit
        # collectives ARE predictable — but only at pp = 1 with
        # tp_overlap off (the pp engines keep their stage-stacked
        # ring/a2a kernels and reject hier for cp/sp layers, and rings
        # cannot nest under the lane vmap anyway)
        raise ValueError("collective-count prediction models Megatron-TP "
                         "plans only (no Ulysses / cp ring layers)")
    m = max(num_microbatches if num_microbatches is not None
            else hpc.chunks, 1)
    pp = max(hpc.pp_deg, 1)
    T = m + 2 * (pp - 1)
    lps = hpc.pp_division[0] if hpc.pp_division else len(hpc.layers)
    out: Dict[str, int] = {}
    if pp > 1:
        out["ppermute_pp"] = 2 * T
    tp = s.tp_size
    if tp_overlap and tp > 1:
        rings_per_tick = 4 + 8 + (4 if s.checkpoint else 0)
        out["ppermute_tp"] = T * lps * rings_per_tick * (tp - 1)
    if hier_dp:
        if s.dp_size < 2:
            raise ValueError("hier_dp prediction needs dp > 1 "
                             "(eligibility.hier_dp_unsupported_reason)")
        if dp_schedule:
            sched = _dp_schedule_from_plan(
                dp_schedule, s.dp_size, hier_cross, hier_bucket_mb)
            out["ppermute_dp"] = sched.n_exchanges
            return out
        n_buckets = 1
        if hier_bucket_mb > 0:
            from hetu_galvatron_tpu.ops.hier_reduce import (
                hier_bucket_layout,
            )

            local, _, intra = _hier_payload_elems_from_plan(
                hpc, model, cross=hier_cross)
            n_buckets = len(hier_bucket_layout(local, intra,
                                               hier_bucket_mb))
        out["reduce_scatter"] = n_buckets
        out["all_reduce"] = n_buckets
        out["all_gather"] = n_buckets
    return out


def plan_collective_bytes(
    hpc,
    model,
    *,
    num_microbatches: Optional[int] = None,
    tp_overlap: bool = True,
    elem_bytes: int = 4,
    hier_dp: bool = False,
    hier_cross: int = 1,
    hier_bucket_mb: float = 0.0,
    dp_schedule: Optional[str] = None,
) -> Dict[str, float]:
    """Predicted per-device EXECUTED explicit-collective megabytes for the
    compiled single-program 1F1B step — the byte-side companion of
    :func:`plan_collective_counts` (counts) and :func:`plan_comm_volume`
    (per-microbatch message megabytes), consumed by the sharding-flow
    byte census (``analysis/sharding_flow.py``).

    Derivation (same message arithmetic as :func:`plan_comm_volume`'s
    ``act_mb = lbsz * seq * h * elem``, re-expressed in the executed
    schedule's units):

    * **tp rings** — each of :func:`plan_collective_counts`'s
      ``T * lps * rings_per_tick * (tp-1)`` ppermute hops carries one
      per-device sequence chunk ``act_mb / tp`` (``ops/overlap.py`` rings
      rotate ``[lbsz, seq/tp, hidden]`` blocks; every fwd/bwd/recompute
      ring's hop payload is that same chunk shape).
    * **pp rotations** — ``2 * T`` stage rotations, each moving one
      per-device slice of the stacked activation: ``act_mb / tp`` under
      Megatron-SP (the boundary activation is sequence-sharded over tp),
      the full ``act_mb`` at tp = 1.

    Counts include the masked bubble ticks (T = m + 2(pp-1)), exactly as
    the traced program executes them — so traced bytes == predicted bytes
    with no tolerance. ``elem_bytes`` must match the traced compute dtype
    (the census traces in f32 → 4; note a bf16 program would ALSO move
    f32 ring accumulators, which this arithmetic does not model — trace
    in f32 to cross-check).

    Raises ValueError for plan shapes the prediction does not model, the
    same gate as :func:`plan_collective_counts` (non-uniform strategies,
    Ulysses/cp layers).
    """
    s = hpc.layers[0]
    if any(l != s for l in hpc.layers):
        raise ValueError("collective-byte prediction needs a uniform "
                         "per-layer strategy (the compiled engine's gate)")
    if (s.sp or s.cp_size > 1) and (
            not hier_dp or tp_overlap or max(hpc.pp_deg, 1) > 1):
        # same relaxation (and same pp = 1 bound) as
        # plan_collective_counts: the hier lane path carries no
        # cp/ulysses kernels, so its explicit bytes are exact
        raise ValueError("collective-byte prediction models Megatron-TP "
                         "plans only (no Ulysses / cp ring layers)")
    m = max(num_microbatches if num_microbatches is not None
            else hpc.chunks, 1)
    pp = max(hpc.pp_deg, 1)
    tp = max(s.tp_size, 1)
    T = m + 2 * (pp - 1)
    lps = hpc.pp_division[0] if hpc.pp_division else len(hpc.layers)
    lbsz = max(hpc.global_bsz // m // max(s.dp_size, 1), 1)
    act_mb = lbsz * model.seq_length * model.hidden_size * elem_bytes / MB
    out: Dict[str, float] = {}
    if pp > 1:
        out["ppermute_pp"] = 2 * T * act_mb / tp
    if tp_overlap and tp > 1:
        rings_per_tick = 4 + 8 + (4 if s.checkpoint else 0)
        out["ppermute_tp"] = (T * lps * rings_per_tick * (tp - 1)
                              * act_mb / tp)
    if hier_dp:
        # hierarchical dp reduction payloads (fp32 accumulators — the
        # reduce casts every leaf to f32, independent of elem_bytes): the
        # concatenated per-device grad vector split into buckets by the
        # SAME hier_bucket_layout the runtime slices with (one bucket at
        # the 0 default), each independently zero-padded to the
        # intra-host degree. Input-aval convention, matching the flow
        # pass: rs moves each bucket's padded vector, ar and ag its
        # 1/intra shard — summed per collective kind.
        if s.dp_size < 2:
            raise ValueError("hier_dp prediction needs dp > 1 "
                             "(eligibility.hier_dp_unsupported_reason)")
        from hetu_galvatron_tpu.ops.hier_reduce import hier_bucket_layout

        local, _, intra = _hier_payload_elems_from_plan(
            hpc, model, cross=hier_cross)
        if dp_schedule:
            # synthesized-schedule path: every exchange step is one
            # ppermute whose traced input aval is [K, c] on EVERY rank
            # (uniform SPMD tables; K = the step's widest transfer, c =
            # the chunk size after the emitter's pad to n_chunks) — so
            # the flow pass's summed input megabytes are Σ_steps K·c·4.
            # The hand-built reference bodies move the identical per-hop
            # payloads (that is the byte half of the parity contract).
            sched = _dp_schedule_from_plan(
                dp_schedule, s.dp_size, hier_cross, hier_bucket_mb)
            c = sched.chunk_elems(local)
            sent = sum(sched.step_max_chunks_sent(st)
                       for st in sched.steps if st.op == "exchange")
            out["ppermute_dp"] = sent * c * 4 / MB
            return out
        layout = hier_bucket_layout(local, intra, hier_bucket_mb)
        out["reduce_scatter"] = sum(p for _, p in layout) * 4 / MB
        out["all_reduce"] = sum(p // intra for _, p in layout) * 4 / MB
        out["all_gather"] = sum(p // intra for _, p in layout) * 4 / MB
    return out


def plan_tp_overlap_hidden_frac(hpc, model, overlapped: Sequence[int],
                                mixed_precision: bool = True) -> float:
    """Predicted fraction of the plan's TP collective traffic hidden under
    compute by the decomposed overlap matmuls: the volume-weighted share
    (``plan_comm_volume``'s per-layer ``tp_collective_mb``) carried by the
    layers actually running overlapped (``overlapped`` = indices where
    ops/overlap.plan_overlap_reasons reported None). In the cost model's
    compute-bound regime that traffic is hidden up to the overlap-slowdown
    residue (cost_model.cost.tp_overlap_hidden_frac); this gauge reports
    the coverage term, which needs no hardware profile at runtime."""
    vols = plan_comm_volume(hpc.layers, model, global_bsz=hpc.global_bsz,
                            chunks=max(hpc.chunks, 1),
                            mixed_precision=mixed_precision)
    total = sum(v["tp_collective_mb"] for v in vols)
    if not total:
        return 0.0
    hidden = sum(vols[i]["tp_collective_mb"] for i in overlapped)
    return hidden / total


def emit_plan_telemetry(registry: MetricsRegistry, hpc, model,
                        mixed_precision: bool = True) -> None:
    """Emit the plan's predicted comm volume as ONE ``plan`` event at
    startup. The per-layer numbers are constants of the plan, so they ride
    the one-shot event's ``layers`` list instead of registered gauges —
    gauges re-snapshot into the sink on EVERY registry flush, which
    duplicated ~4*layers identical records per flush for the whole run."""
    vols = plan_comm_volume(hpc.layers, model, global_bsz=hpc.global_bsz,
                            chunks=max(hpc.chunks, 1),
                            mixed_precision=mixed_precision)
    total = sum(v["total_mb"] for v in vols)
    registry.event("plan", {
        "global_bsz": hpc.global_bsz, "chunks": hpc.chunks,
        "pp_deg": hpc.pp_deg, "predicted_comm_mb_per_step": total,
        "layers": [
            {"layer": i,
             **{coll: mb for coll, mb in v.items() if mb}}
            for i, v in enumerate(vols)],
    })
