"""Goodput accounting: wall-clock partitioned by what it bought.

A preemptible fleet's real throughput is not step time — it is the
fraction of wall-clock that produced committed training progress. The
tracker partitions elapsed time into:

* ``productive_step`` — steps whose updates survived (the numerator),
* ``recompile``       — first-step jit compilation per attempt,
* ``checkpoint_save`` — blocking save time at commit points,
* ``resume_replay``   — checkpoint restore + data-stream fast-forward,
* ``reshard``         — elastic topology changes: the re-search for the
  new world plus the cross-plan checkpoint reshard
  (``runtime/reshard.py``), so "what did losing half the fleet cost"
  is a gauge, not a guess,
* ``restart_lost``    — everything a restart threw away: post-commit
  steps of the dead attempt, downtime, supervisor backoff.

``goodput() = productive_step / sum(everything tracked)``.

Restart accounting needs no cross-process channel: :meth:`state_dict`
(stored in the checkpoint's ``train_state`` payload at every save)
carries the totals *as of the commit* plus a wall-clock stamp.
:meth:`load_state_dict` on resume restores those totals and books
``now - stamp`` as ``restart_lost`` — which by construction includes the
dead attempt's discarded post-commit work, the gap to the restart, and
the supervisor's backoff sleep, without double counting (the dead
attempt's post-commit productive time was never committed to any
snapshot). ``goodput/*`` gauges therefore survive preemption exactly as
far as the checkpoint does — the same durability contract as the model
state itself.

Everything here is host-side ``time`` arithmetic: no device values, no
syncs.
"""

from __future__ import annotations

import time
from contextlib import contextmanager
from typing import Any, Callable, Dict, Optional

CATEGORIES = ("productive_step", "recompile", "checkpoint_save",
              "resume_replay", "reshard", "restart_lost")


class GoodputTracker:
    """Accumulates per-category seconds; snapshot/restore via the
    checkpoint ``train_state`` payload. ``clock`` (monotonic, durations)
    and ``wall`` (epoch, cross-process gaps) are injectable for tests."""

    def __init__(self, *, clock: Callable[[], float] = time.perf_counter,
                 wall: Callable[[], float] = time.time):
        self._clock = clock
        self._wall = wall
        self.totals: Dict[str, float] = {c: 0.0 for c in CATEGORIES}
        self.restarts_survived = 0

    # -- accumulation -------------------------------------------------------

    def add(self, category: str, seconds: float) -> None:
        if seconds > 0:
            self.totals[category] = self.totals.get(category, 0.0) + seconds

    @contextmanager
    def measure(self, category: str):
        t0 = self._clock()
        try:
            yield
        finally:
            self.add(category, self._clock() - t0)

    # -- derived ------------------------------------------------------------

    def total(self) -> float:
        return sum(self.totals.values())

    def goodput(self) -> float:
        """Productive share of all tracked wall-clock (1.0 when nothing
        was tracked yet — an unstarted run has lost nothing)."""
        total = self.total()
        if total <= 0:
            return 1.0
        return self.totals.get("productive_step", 0.0) / total

    # -- persistence (checkpoint train_state payload) -----------------------

    def state_dict(self) -> Dict[str, Any]:
        """Snapshot for the checkpoint: totals as of this commit plus a
        wall-clock stamp the resuming process diffs against."""
        return {"totals": dict(self.totals), "wall_time": self._wall(),
                "restarts_survived": self.restarts_survived}

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        """Merge a committed snapshot into this (fresh) tracker: prior
        totals accumulate, and the wall-clock gap since the commit is
        booked as ``restart_lost`` — the dead attempt's discarded
        post-commit work plus all downtime and backoff."""
        for k, v in (state.get("totals") or {}).items():
            self.totals[k] = self.totals.get(k, 0.0) + float(v)
        self.restarts_survived = int(state.get("restarts_survived", 0)) + 1
        stamp = state.get("wall_time")
        if stamp is not None:
            self.add("restart_lost", max(0.0, self._wall() - float(stamp)))

    # -- export -------------------------------------------------------------

    def flush(self, registry: Any) -> None:
        """Set the ``goodput/*`` gauges (cumulative seconds per category,
        the goodput fraction, and restarts survived) into ``registry``."""
        for c in CATEGORIES:
            registry.gauge(f"goodput/{c}_s").set(self.totals.get(c, 0.0))
        registry.gauge("goodput/goodput_frac").set(self.goodput())
        registry.gauge("goodput/restarts_survived").set(
            self.restarts_survived)
