"""Crash-forensics flight recorder: a bounded ring of recent events +
metric snapshots, dumped atomically on fault.

A dead run should leave a self-contained postmortem artifact. The
recorder keeps the last N structured events (tapped off an
:class:`~hetu_galvatron_tpu.observability.events.EventStream` and/or
recorded directly via :meth:`note`) in a ``collections.deque`` ring; on a
fault, signal, or NaN-halt, :meth:`dump` snapshots every registry metric
and writes one ``flight_<ts>.json`` with the same tmp+rename atomicity
discipline as checkpoints (``runtime/checkpoint.py::_commit``) — a
torn dump is a ``.tmp`` file readers never select, not a half-valid JSON.

The dump contract mirrors PR 6's audit hook: **dumping must never mask
the real traceback**. Every failure inside :meth:`dump` is swallowed into
``last_error`` and the method returns ``None`` — the caller's crash path
(engine abort, trainer finally, PreemptionGuard) re-raises the *original*
fault untouched.

Registration points:

* ``serving/engine.py`` — taps the engine's event stream, dumps on
  ``_abort`` (fatal engine-thread error) when ``serving.flight_dir`` is
  set.
* ``runtime/supervisor.py::PreemptionGuard`` — dumps on the first
  trapped signal (from the main thread, at the step-boundary check, not
  inside the async handler).
* ``cli/train_dist.py`` — dumps on crash (the run_loop except path) and
  on rerun-machine halt codes (NaN / validation faults).

``cli/summarize.py`` renders a dump (and warns-and-skips a torn one).
"""

from __future__ import annotations

import collections
import json
import os
import time
import traceback
from typing import Any, Dict, List, Optional

from hetu_galvatron_tpu.observability.registry import (
    MetricsRegistry,
    get_registry,
)

# schema marker cli/summarize.py dispatches on
FLIGHT_KIND = "flight_recorder"


def _jsonable(x: Any) -> Any:
    """Last-resort encoder (numpy/jax scalars -> numbers, else str) —
    the dump must serialize whatever the ring happened to capture."""
    try:
        return float(x)
    except (TypeError, ValueError):
        return str(x)


class FlightRecorder:
    """Bounded in-memory black box with an atomic crash dump.

    ``capacity`` bounds the ring (oldest events fall off); ``out_dir``
    is where dumps land — ``None`` keeps the ring alive (taps still
    record) but makes :meth:`dump` a counted no-op, so engines that did
    not opt into an artifact directory never litter the filesystem.
    """

    def __init__(self, *, capacity: int = 256,
                 registry: Optional[MetricsRegistry] = None,
                 out_dir: Optional[str] = None,
                 prefix: str = "flight"):
        self.capacity = max(int(capacity), 1)
        self._registry = registry
        self.out_dir = out_dir
        self.prefix = prefix
        self._ring: "collections.deque[Dict[str, Any]]" = collections.deque(
            maxlen=self.capacity)
        # pinned records (retain()): keyed by name, latest wins, never
        # evicted by ring pressure — calibration state (last plan_audit /
        # plan_regret) must survive into a dump taken thousands of events
        # later
        self._retained: Dict[str, Dict[str, Any]] = {}
        self.dumped: List[str] = []  # paths of successful dumps
        self.last_error: Optional[BaseException] = None

    @property
    def registry(self) -> MetricsRegistry:
        return (self._registry if self._registry is not None
                else get_registry())

    # -- recording ----------------------------------------------------------

    def record(self, name: str, data: Dict[str, Any]) -> None:
        """Tap-shaped entry point (``EventStream.add_tap(recorder.record)``)."""
        self._ring.append({"name": name, "data": data})

    def attach(self, events: Any) -> "FlightRecorder":
        """Subscribe to an :class:`EventStream`; returns self for chaining."""
        events.add_tap(self.record)
        return self

    def note(self, name: str, **data: Any) -> None:
        """Record one ad-hoc entry (timestamps like the event stream)."""
        self.record(name, {"ev": name, "tm": time.monotonic() * 1000.0,
                           **data})

    def retain(self, name: str, data: Dict[str, Any]) -> None:
        """Pin one record outside the ring: the LAST ``retain(name, ...)``
        per name is carried in every later :meth:`snapshot` under
        ``retained`` regardless of how many ring events have since
        evicted it. Used for low-frequency, high-value state — the last
        ``plan_audit`` / ``plan_regret`` — so post-crash triage sees the
        calibration picture at failure time."""
        self._retained[str(name)] = {"name": str(name),
                                     "t": time.time(), "data": data}

    def events(self) -> List[Dict[str, Any]]:
        return list(self._ring)

    # -- dumping ------------------------------------------------------------

    def snapshot(self, reason: str,
                 exc: Optional[BaseException] = None) -> Dict[str, Any]:
        """The dump payload: reason, optional exception (type + message +
        traceback), the event ring, and a snapshot of every registry
        metric — self-contained, no other file needed to read it."""
        metrics = []
        for m in self.registry.metrics():
            rec: Dict[str, Any] = {"kind": m.kind, "name": m.name}
            if m.labels:
                rec["labels"] = m.labels
            rec.update(m.snapshot())
            metrics.append(rec)
        payload: Dict[str, Any] = {
            "kind": FLIGHT_KIND,
            "reason": reason,
            "t": time.time(),
            "pid": os.getpid(),
            "exception": None,
            "events": list(self._ring),
            "retained": dict(self._retained),
            "metrics": metrics,
        }
        if exc is not None:
            payload["exception"] = {
                "type": type(exc).__name__,
                "message": str(exc),
                "traceback": "".join(traceback.format_exception(
                    type(exc), exc, exc.__traceback__)),
            }
        return payload

    def dump(self, reason: str, exc: Optional[BaseException] = None,
             out_dir: Optional[str] = None) -> Optional[str]:
        """Write ``flight_<ts>.json`` atomically (tmp + rename); returns
        the path, or ``None`` when no directory is configured or anything
        failed. NEVER raises — the crash path that calls this must
        surface its own fault, not the recorder's."""
        d = out_dir if out_dir is not None else self.out_dir
        if not d:
            return None
        try:
            payload = self.snapshot(reason, exc)
            os.makedirs(d, exist_ok=True)
            ts = int(payload["t"] * 1000.0)
            path = os.path.join(d, f"{self.prefix}_{ts}_{os.getpid()}.json")
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(payload, f, default=_jsonable)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
            self.dumped.append(path)
            return path
        except Exception as e:  # noqa: BLE001 — dumping must never mask
            # the real fault; the failure is kept for postmortem asserts
            self.last_error = e
            return None
