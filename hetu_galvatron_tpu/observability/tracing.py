"""Host-side trace spans + windowed XLA device-trace capture.

``span("fwd")`` measures host wall-clock for a code region AND enters a
``jax.profiler.TraceAnnotation``, so the same name shows up on the host
track of an XLA device trace (captured with :class:`TraceCapture` /
``jax.profiler.start_trace``, viewed in tensorboard/xprof). Under async
dispatch a host span around jitted calls measures DISPATCH time, not device
time — that is the point: a hot dispatch loop (e.g. the pipeline
controller) shows up here, while device time lives in the captured trace
under the same annotation names.

Span durations aggregate into the registry as ``span_ms`` histograms
labelled by the nesting path (``train/step``, ``pp/fwd_s0``, ...), so
per-iteration spans cost one histogram observe — no per-span records, no
unbounded JSONL growth.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Optional

from hetu_galvatron_tpu.observability.registry import (
    MetricsRegistry,
    get_registry,
)

_tls = threading.local()


def current_span_path() -> str:
    """Slash-joined names of the open spans on this thread ('' outside)."""
    return "/".join(getattr(_tls, "stack", []))


@contextmanager
def span(name: str, registry: Optional[MetricsRegistry] = None):
    """Measure a region; nests ('train/step' inside 'train' -> path
    'train/train/step' is avoided by naming spans hierarchically at the
    call site). Re-entrant and thread-safe (per-thread stacks)."""
    import jax

    reg = registry or get_registry()
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    stack.append(name)
    path = "/".join(stack)
    t0 = time.perf_counter()
    try:
        with jax.profiler.TraceAnnotation(name):
            yield
    finally:
        dur_ms = (time.perf_counter() - t0) * 1000.0
        stack.pop()
        reg.histogram("span_ms", path=path).observe(dur_ms)


class TraceCapture:
    """Opt-in windowed ``jax.profiler.start_trace`` capture.

    ``step(it)`` starts the trace when ``it`` ENTERS the window
    [start_iter, start_iter + num_iters) and stops it on leaving; the
    window test is ">= start" (not "=="), so a checkpoint-resumed run whose
    first iteration is already past ``start_iter`` still captures a full
    window. One capture per process lifetime; rank-gating is the caller's
    job (pass ``enabled=False`` on non-zero ranks).
    """

    def __init__(self, trace_dir: str, start_iter: int = 0,
                 num_iters: int = 3, enabled: bool = True):
        self.trace_dir = trace_dir
        self.start_iter = start_iter
        self.num_iters = num_iters
        self.enabled = bool(enabled and trace_dir)
        self.active = False
        self._captured = 0

    def step(self, it: int) -> bool:
        """Advance the window; returns True while this iteration is being
        traced (callers keep traced iterations out of timing stats — the
        instrumentation inflates step time)."""
        if not self.enabled:
            return False
        if self.active:
            self._captured += 1
            if self._captured >= self.num_iters:
                self.stop()
            return self.active
        if self._captured == 0 and it >= self.start_iter:
            import jax

            jax.profiler.start_trace(self.trace_dir)
            self.active = True
            return True
        return False

    def stop(self) -> None:
        """Idempotent; call at loop exit so short/crashing runs still flush
        the capture."""
        if self.active:
            import jax

            jax.profiler.stop_trace()
            self.active = False
