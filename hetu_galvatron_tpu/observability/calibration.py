"""Self-calibrating cost model: the refit half of the observability loop.

``trace_analysis.audit_plan`` diffs measured vs predicted collective time
per component every traced run — this module stops dropping those numbers
on the floor and closes the loop::

    profile ──> search ──> run ──> audit ──> refit ──> regret
      (prior)    (plan)   (trace)  (residuals) (posterior)  (alarm)

Three pieces, glued by :func:`run_calibration` (wired into the loop-exit
audit hook in ``cli/train_dist.py``):

1. **Persistent residual store** (:class:`ResidualStore`): every plan
   audit appends per-curve ``(message MB, measured ms)`` observations —
   derived from the audit table with exactly the message arithmetic
   ``predicted_comm_per_step`` prices with — to an append-only JSONL
   file keyed by a hardware fingerprint (device kind, world size, mesh
   shape). Appends are single-``os.write`` on an ``O_APPEND`` fd so
   concurrent supervisor restarts interleave whole lines; the reader
   skips torn or foreign lines with a warning, never a traceback (the
   PR 6 summarize contract).
2. **α-β re-fitter** (:func:`refit_profile`): robust regression
   (min-sample-gated, MAD outlier-rejecting, reusing
   ``hardware_profiler.fit_alpha_beta``'s degenerate-slope hardening)
   over the accumulated points per ``(group, algorithm, level)`` curve.
   Single-size point clouds — the common steady-production case — fall
   back to a *scale* calibration against the prior curve (α·r, β/r with
   r the median measured/predicted ratio), so one-shot profiling is the
   prior and production traces the posterior. The emitted JSON lives in
   the exact key namespace ``profiles.read_alpha_beta`` /
   ``read_alpha_beta_algos`` already parse, provenance-tagged under a
   ``calibration_meta`` key (source, per-curve point counts + method,
   fit window, fingerprint) that both parsers and the summarize router
   ignore — profiled and calibrated curves coexist, and the search
   engine consumes whichever file the operator points it at.
3. **Plan-regret drift sentinel** (:func:`evaluate_plan_regret`): the
   search engine embeds its top-k runner-up strategies (priced ms each)
   in the winning plan JSON; the audit hook re-prices incumbent +
   runner-ups under the calibrated curves
   (``cost_model.reprice_stored_plan_ms``) and publishes
   ``calibration/plan_regret_ms`` + ``calibration/drift_score`` gauges,
   raising one ``plan_regret`` event when a runner-up now beats the
   incumbent by more than ``observability.regret_threshold`` — "the
   plan went stale" becomes a measured, alarmable signal instead of a
   silent throughput loss.

Everything here is post-mortem/loop-exit machinery: :func:`run_calibration`
never raises (it runs in the same crash-path ``finally`` block as the
audit itself).
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from hetu_galvatron_tpu.observability.registry import (
    MetricsRegistry,
    get_registry,
)

MB = 1024 * 1024

# file names under observability.calibration_dir
STORE_NAME = "residuals.jsonl"
PROFILE_NAME = "calibrated_profile.json"

# provenance key both α-β parsers and the summarize hardware router ignore
META_KEY = "calibration_meta"


# ---------------------------------------------------------------------------
# hardware fingerprint
# ---------------------------------------------------------------------------


def hardware_fingerprint(hpc: Any = None, *, world: Optional[int] = None,
                         device_kind: Optional[str] = None
                         ) -> Dict[str, Any]:
    """Identity of the hardware the residuals were measured on: device
    kind, world size, and the plan's mesh shape ``[pp, tp, dp]``. Points
    from a different fingerprint never pollute a fit — a v5e curve must
    not be refit from v4 residuals, nor an 8-chip curve from a 4-chip
    run."""
    if device_kind is None:
        try:
            import jax

            device_kind = jax.devices()[0].device_kind
        except Exception:  # noqa: BLE001 — post-mortem helper
            device_kind = "unknown"
    mesh: List[int] = []
    if hpc is not None:
        layers = getattr(hpc, "layers", None) or []
        s0 = layers[0] if layers else None
        mesh = [int(getattr(hpc, "pp_deg", 1) or 1),
                int(s0.tp_size) if s0 is not None else 1,
                int(s0.dp_size) if s0 is not None else 1]
        if world is None:
            world = getattr(hpc, "world_size", None)
    return {"device": str(device_kind), "world": int(world or 0),
            "mesh": mesh}


def fingerprint_key(fp: Dict[str, Any]) -> str:
    """Stable short form for logs and meta tags."""
    mesh = "x".join(str(int(m)) for m in fp.get("mesh", []) or [])
    dev = str(fp.get("device", "unknown")).replace(" ", "-")
    return f"{dev}_w{int(fp.get('world', 0))}_{mesh or 'nomesh'}"


def _fp_matches(a: Optional[Dict[str, Any]], b: Optional[Dict[str, Any]]
                ) -> bool:
    if not isinstance(a, dict) or not isinstance(b, dict):
        return False
    return (str(a.get("device")) == str(b.get("device"))
            and int(a.get("world", 0)) == int(b.get("world", 0))
            and list(a.get("mesh") or []) == list(b.get("mesh") or []))


# ---------------------------------------------------------------------------
# persistent residual store
# ---------------------------------------------------------------------------


class ResidualStore:
    """Append-only JSONL of per-curve residual observations, accumulated
    across runs and supervisor restarts.

    Writes go through one ``os.write`` on an ``O_APPEND`` descriptor per
    batch — concurrent multi-process appenders interleave whole batches,
    not bytes. Reads tolerate torn trailing lines, corrupt records, and
    foreign fingerprints: bad lines are counted in ``skipped`` and warned
    to stderr once per load, never raised."""

    def __init__(self, path: str):
        self.path = path
        self.skipped = 0

    def append(self, points: Sequence[Dict[str, Any]], *,
               fingerprint: Dict[str, Any],
               run_id: Optional[str] = None) -> int:
        """Append one audit's points (each tagged with the fingerprint and
        a wall timestamp); returns how many were written."""
        if not points:
            return 0
        now = time.time()
        lines = []
        for p in points:
            rec = dict(p)
            rec.setdefault("t", now)
            rec["fp"] = fingerprint
            if run_id is not None:
                rec["run"] = str(run_id)
            lines.append(json.dumps(rec, separators=(",", ":"),
                                    default=_jsonable))
        # leading newline: if the previous writer died mid-line, its torn
        # tail gets terminated here and only THAT line is lost — without
        # it the torn tail would concatenate onto (and swallow) this
        # batch's first record. Blank lines are skipped by load() without
        # counting as corruption.
        payload = ("\n" + "\n".join(lines) + "\n").encode("utf-8")
        d = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(d, exist_ok=True)
        fd = os.open(self.path, os.O_WRONLY | os.O_CREAT | os.O_APPEND,
                     0o644)
        try:
            os.write(fd, payload)
        finally:
            os.close(fd)
        return len(lines)

    def load(self, *, fingerprint: Optional[Dict[str, Any]] = None
             ) -> List[Dict[str, Any]]:
        """Every parseable point (optionally fingerprint-filtered).
        ``self.skipped`` counts dropped lines of the last load."""
        self.skipped = 0
        out: List[Dict[str, Any]] = []
        try:
            with open(self.path, encoding="utf-8", errors="replace") as f:
                raw = f.read()
        except OSError:
            return out
        for line in raw.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                self.skipped += 1
                continue
            if not isinstance(rec, dict):
                self.skipped += 1
                continue
            if fingerprint is not None and not _fp_matches(
                    rec.get("fp"), fingerprint):
                continue
            out.append(rec)
        if self.skipped:
            print(f"calibration: skipped {self.skipped} unparseable "
                  f"line(s) in {self.path} (torn/concurrent append)",
                  file=sys.stderr)
        return out


def _jsonable(x: Any) -> Any:
    try:
        return float(x)
    except (TypeError, ValueError):
        return str(x)


# ---------------------------------------------------------------------------
# residual extraction from one audit table
# ---------------------------------------------------------------------------


def calibration_points(table: Dict[str, Any], hpc: Any, model: Any, *,
                       mixed_precision: bool = True
                       ) -> List[Dict[str, Any]]:
    """Per-curve ``(message MB, measured per-message ms)`` observations
    from one ``audit_plan`` table, using exactly the message arithmetic
    ``predicted_comm_per_step`` prices with (so a refit curve predicts the
    same quantity the audit measures).

    tp: the component's measured ms is apportioned across (tp size,
    activation MB) groups by their bandwidth-dominated share and divided
    by the group's message count — one point per group on the
    ``"{tp}_1"`` curve, attributed to the algorithm the audit chose
    (``flat`` when no per-algorithm curves priced it). dp: same, per
    flat-ring gradient buffer on ``"{sdp}_{consec}"``. A plan running the
    hierarchical dp reduction contributes no dp points — its measured dp
    time is one concatenated three-collective schedule, not the per-layer
    flat rings these curves model (the hier decomposition rows stay
    audit-only)."""
    from hetu_galvatron_tpu.observability.telemetry import layer_param_mb

    rows = [r for r in (table.get("rows") or []) if isinstance(r, dict)]
    by_comp = {str(r.get("component")): r for r in rows}
    chosen_tp_alg = "flat"
    for r in rows:
        c = str(r.get("component", ""))
        if c.startswith("tp[") and c.endswith("]") and r.get("chosen"):
            chosen_tp_alg = c[3:-1]
    points: List[Dict[str, Any]] = []
    layers = getattr(hpc, "layers", None) or []
    if not layers:
        return points
    chunks = max(int(getattr(hpc, "chunks", 1) or 1), 1)
    pp = max(int(getattr(hpc, "pp_deg", 1) or 1), 1)
    seq, h = model.seq_length, model.hidden_size
    elem = 2 if mixed_precision else 4
    param_mb = layer_param_mb(model)

    def _apportion(groups: Dict[Tuple, List[float]], measured: float,
                   alg: str, group_of) -> None:
        # share by w·mb (bandwidth-dominated proxy); exact in the common
        # single-group case where no apportioning happens at all
        shares = {k: g[1] * g[0] for k, g in groups.items()}
        tot = sum(shares.values())
        if tot <= 0:
            return
        for key, (mb, w) in groups.items():
            if w <= 0:
                continue
            ms = measured * shares[key] / tot / w
            if ms <= 0 or mb <= 0:
                continue
            points.append({"collective": "allreduce",
                           "group": group_of(key), "alg": alg,
                           "mb": round(mb, 9), "ms": round(ms, 9),
                           "w": round(w, 6)})

    # tp (Megatron-SP ag/rs-equivalent messages on the "{tp}_1" curve)
    tp_groups: Dict[Tuple, List[float]] = {}
    for s in layers:
        tp = 1 if s.sp else s.tp_size
        if tp <= 1:
            continue
        lbsz = max(hpc.global_bsz // chunks // max(s.dp_size, 1), 1)
        act_mb = lbsz * seq * h * elem / MB
        w = 6 * chunks * (1.5 if s.checkpoint else 1.0) * 0.5 / pp
        g = tp_groups.setdefault((tp, round(act_mb, 9)), [act_mb, 0.0])
        g[1] += w
    trow = by_comp.get("tp")
    if tp_groups and trow and trow.get("measured_ms"):
        _apportion(tp_groups, float(trow["measured_ms"]), chosen_tp_alg,
                   lambda key: f"{key[0]}_1")

    # dp (flat per-layer gradient rings; hier plans contribute nothing)
    if "dp[hier]" not in by_comp:
        dp_groups: Dict[Tuple, List[float]] = {}
        for s in layers:
            tp = 1 if s.sp else s.tp_size
            sdp = max(s.dp_size * s.cp_size * (s.tp_size if s.sp else 1), 1)
            if sdp <= 1:
                continue
            grad_mb = param_mb / max(tp, 1) * \
                (0.5 if mixed_precision else 1.0)
            key = (sdp, 1 if tp == 1 else 0, round(grad_mb, 9))
            g = dp_groups.setdefault(key, [grad_mb, 0.0])
            g[1] += 1.0 / pp
        drow = by_comp.get("dp")
        if dp_groups and drow and drow.get("measured_ms"):
            _apportion(dp_groups, float(drow["measured_ms"]), "flat",
                       lambda key: f"{key[0]}_{key[1]}")
    return points


def drift_score(table: Dict[str, Any]) -> Optional[float]:
    """Aggregate model drift from one audit table:
    Σ|measured−predicted| / Σpredicted over the top-level components that
    carried a time prediction (0 = the curves still price reality)."""
    num = den = 0.0
    for r in table.get("rows") or []:
        if not isinstance(r, dict) or "[" in str(r.get("component", "")):
            continue
        p = r.get("predicted_ms")
        if not isinstance(p, (int, float)) or p <= 0:
            continue
        m = r.get("measured_ms")
        if not isinstance(m, (int, float)):
            continue
        num += abs(float(m) - float(p))
        den += float(p)
    return (num / den) if den > 0 else None


# ---------------------------------------------------------------------------
# α-β re-fitter
# ---------------------------------------------------------------------------


def _robust_fit(pts: List[Tuple[float, float, float]], *, outlier_k: float,
                min_rel_spread: float, label: str
                ) -> Tuple[Optional[Tuple[float, float]], int]:
    """Outlier-rejecting α-β regression over (mb, ms, weight) points.
    Returns ((α, β), points_used) or (None, n) when the sizes carry no
    spread (single size / zero variance) or the slope is degenerate —
    the caller then falls back to scale calibration."""
    from hetu_galvatron_tpu.core.profiler.hardware_profiler import (
        fit_alpha_beta,
    )

    xs = np.asarray([p[0] for p in pts], dtype=np.float64)
    ys = np.asarray([p[1] for p in pts], dtype=np.float64)
    lo, hi = float(xs.min()), float(xs.max())
    if hi <= 0 or (hi - lo) / hi < min_rel_spread:
        return None, len(pts)
    fit = fit_alpha_beta(xs, ys, label=label)
    if fit is None:
        return None, len(pts)
    alpha, beta = fit
    res = ys - (alpha + xs / beta)
    med = float(np.median(res))
    mad = float(np.median(np.abs(res - med)))
    if mad > 0:
        keep = np.abs(res - med) <= outlier_k * mad
        n_keep = int(keep.sum())
        if 2 <= n_keep < len(xs):
            xs2, ys2 = xs[keep], ys[keep]
            if float(xs2.max()) > float(xs2.min()):
                refit = fit_alpha_beta(xs2, ys2,
                                       label=f"{label} (outliers dropped)")
                if refit is not None:
                    return refit, n_keep
    return fit, len(pts)


def window_points(points: Sequence[Dict[str, Any]], *,
                  window_days: float = 0.0,
                  max_points_per_curve: int = 0,
                  now: Optional[float] = None) -> List[Dict[str, Any]]:
    """Decay + window the residual store before a re-fit.

    ``window_days > 0`` drops points whose wall timestamp (``t``, stamped
    by :meth:`ResidualStore.append`) is older than that many days —
    hardware or software changes age out of the posterior instead of
    anchoring it forever. Points carrying no timestamp are of unknown
    age and are dropped too under an active window (legacy pre-timestamp
    lines; keeping them would defeat the decay).

    ``max_points_per_curve > 0`` then keeps only that many NEWEST points
    per ``(group, alg)`` curve key, bounding both the fit cost and the
    influence of any one flood of appends. 0 disables either limit;
    the default is the historical keep-everything behaviour."""
    pts = [p for p in points if isinstance(p, dict)]
    if window_days > 0:
        cutoff = (now if now is not None else time.time()) \
            - window_days * 86400.0
        pts = [p for p in pts
               if isinstance(p.get("t"), (int, float))
               and float(p["t"]) >= cutoff]
    if max_points_per_curve > 0:
        by_curve: Dict[Tuple[str, str], List[Dict[str, Any]]] = {}
        for p in pts:
            key = (str(p.get("group", "")), str(p.get("alg") or "flat"))
            by_curve.setdefault(key, []).append(p)
        keep = set()
        for recs in by_curve.values():
            newest = sorted(
                recs,
                key=lambda p: float(p["t"]) if isinstance(
                    p.get("t"), (int, float)) else float("-inf"),
            )[-max_points_per_curve:]
            keep.update(id(p) for p in newest)
        pts = [p for p in pts if id(p) in keep]
    return pts


def refit_profile(points: Sequence[Dict[str, Any]], *,
                  prior: Optional[Dict[str, Any]] = None,
                  min_points: int = 4, min_rel_spread: float = 0.05,
                  outlier_k: float = 4.0
                  ) -> Tuple[Dict[str, float], Dict[str, Any]]:
    """Fit calibrated α-β pairs per (group, algorithm) curve from
    accumulated residual points. Returns ``(profile_keys, meta)`` where
    ``profile_keys`` uses the exact ``read_alpha_beta`` /
    ``read_alpha_beta_algos`` namespace and ``meta`` is the
    ``calibration_meta`` provenance payload (per-curve point counts, fit
    method, fit window).

    Per curve: with at least ``min_points`` size-diverse points, a robust
    regression; otherwise, when the prior profiled the curve, a scale
    calibration (median measured/predicted ratio applied as α·r, β/r —
    the posterior update a single-size production workload supports);
    otherwise the curve is skipped."""
    from hetu_galvatron_tpu.core.search_engine.profiles import (
        read_alpha_beta,
        read_alpha_beta_algos,
    )

    prior_cfg = prior or {}
    try:
        prior_flat = read_alpha_beta(prior_cfg)
        prior_algos = read_alpha_beta_algos(prior_cfg)
    except Exception:  # noqa: BLE001 — a corrupt prior degrades, not dies
        prior_flat, prior_algos = {}, {}

    curves: Dict[Tuple[str, str], List[Tuple[float, float, float]]] = {}
    t_vals: List[float] = []
    for p in points:
        if not isinstance(p, dict):
            continue
        mb, ms = p.get("mb"), p.get("ms")
        if not isinstance(mb, (int, float)) or not isinstance(
                ms, (int, float)) or mb <= 0 or ms <= 0:
            continue
        group = str(p.get("group", ""))
        parts = group.split("_")
        if len(parts) != 2 or not all(x.isdigit() for x in parts):
            continue
        alg = str(p.get("alg") or "flat")
        w = p.get("w", 1.0)
        w = float(w) if isinstance(w, (int, float)) and w > 0 else 1.0
        curves.setdefault((group, alg), []).append(
            (float(mb), float(ms), w))
        if isinstance(p.get("t"), (int, float)):
            t_vals.append(float(p["t"]))

    cfg: Dict[str, float] = {}
    meta_curves: Dict[str, Dict[str, Any]] = {}
    for (group, alg), pts in sorted(curves.items()):
        fitted = None
        method = None
        used = len(pts)
        if len(pts) >= max(min_points, 2):
            fitted, used = _robust_fit(
                pts, outlier_k=outlier_k, min_rel_spread=min_rel_spread,
                label=f"calibration {group}/{alg}")
            if fitted is not None:
                method = "regression"
        if fitted is None:
            pr = (prior_flat.get(group) if alg == "flat"
                  else (prior_algos.get(group) or {}).get(alg))
            if pr is not None:
                ratios = [ms / (pr[0] + mb / pr[1]) for mb, ms, _ in pts
                          if pr[0] + mb / pr[1] > 0]
                if ratios:
                    r = float(np.median(ratios))
                    r = min(max(r, 0.05), 20.0)
                    fitted = (pr[0] * r, pr[1] / r)
                    method = "scale"
                    used = len(ratios)
        if fitted is None:
            continue
        alpha, beta = max(float(fitted[0]), 0.0), float(fitted[1])
        if beta <= 0:
            continue
        n, c = group.split("_")
        if alg == "flat":
            stem = f"allreduce_size_{n}_consec_{c}"
        else:
            a, _, lvl = alg.rpartition("_")
            if not a or not lvl:
                continue
            stem = f"allreduce_size_{n}_consec_{c}_alg_{a}_lvl_{lvl}"
        cfg[f"{stem}_alpha_ms"] = round(alpha, 9)
        cfg[f"{stem}_beta_mb_per_ms"] = round(beta, 6)
        meta_curves[f"{group}/{alg}"] = {"points": int(used),
                                         "method": method}
    meta: Dict[str, Any] = {"source": "runtime-calibrated",
                            "curves": meta_curves,
                            "fitted_at": time.time()}
    if t_vals:
        meta["window"] = [min(t_vals), max(t_vals)]
    return cfg, meta


def write_calibrated_profile(path: str, cfg: Dict[str, Any]) -> str:
    """Atomic write (tmp + fsync + replace — the flight-dump discipline):
    a reader never sees a torn profile."""
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(cfg, f, indent=2, sort_keys=True, default=_jsonable)
        f.write("\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


def _ensure_bandwidth_keys(cfg: Dict[str, Any]) -> None:
    """Bare ``allreduce_size_{n}_consec_{c}`` bandwidth keys for every
    fitted flat curve that lacks one (summarize's group listing keys off
    them): β IS the fitted effective MB/ms."""
    for key in list(cfg):
        if (key.startswith("allreduce_size_")
                and key.endswith("_beta_mb_per_ms") and "_alg_" not in key):
            bare = key[:-len("_beta_mb_per_ms")]
            cfg.setdefault(bare, cfg[key])


# ---------------------------------------------------------------------------
# plan-regret drift sentinel
# ---------------------------------------------------------------------------


def plan_spec_from_hpc(hpc: Any) -> Dict[str, Any]:
    """The incumbent plan in the stored-strategy shape
    ``cost_model.reprice_stored_plan_ms`` prices (the same shape
    ``save_results`` embeds for each runner-up)."""
    layers = []
    for s in getattr(hpc, "layers", None) or []:
        layers.append({"tp": int(s.tp_size), "dp": int(s.dp_size),
                       "cp": int(s.cp_size), "sp": int(bool(s.sp)),
                       "ckpt": int(bool(s.checkpoint)),
                       "consec": int(bool(s.tp_consecutive))})
    return {"layers": layers, "pp": int(getattr(hpc, "pp_deg", 1) or 1),
            "bsz": int(getattr(hpc, "global_bsz", 1) or 1),
            "chunks": int(getattr(hpc, "chunks", 1) or 1)}


def evaluate_plan_regret(
    incumbent: Dict[str, Any],
    runner_ups: Sequence[Dict[str, Any]],
    *,
    seq_len: int,
    hidden_size: int,
    param_mb: float,
    mixed_precision: bool = True,
    prior: Tuple[Optional[Dict], Optional[Dict]] = (None, None),
    calibrated: Tuple[Optional[Dict], Optional[Dict]] = (None, None),
    threshold: float = 0.05,
) -> Dict[str, Any]:
    """Re-price the incumbent and its stored runner-ups under calibrated
    curves and measure the regret of keeping the incumbent.

    Each candidate's search-time total (``time_cost_ms``) is adjusted by
    the *differential* the calibration implies: ``adjusted = time_cost_ms
    − comm(prior curves) + comm(calibrated curves)`` — the compute and
    schedule terms the search priced are untouched, only the collective
    model moves. ``triggered`` when the best runner-up's adjusted total
    beats the incumbent's by more than ``threshold`` (a fraction of the
    incumbent's adjusted step time). Candidates the curves cannot price
    are skipped, never guessed."""
    from hetu_galvatron_tpu.core.cost_model.cost import (
        reprice_stored_plan_ms,
    )

    def adjusted(plan: Dict[str, Any]) -> Optional[float]:
        t = plan.get("time_cost_ms")
        if not isinstance(t, (int, float)) or t <= 0:
            return None
        kw = dict(seq_len=seq_len, hidden_size=hidden_size,
                  param_mb=param_mb, mixed_precision=mixed_precision)
        pri = reprice_stored_plan_ms(plan, alpha_beta=prior[0],
                                     alpha_beta_algos=prior[1], **kw)
        cal = reprice_stored_plan_ms(plan, alpha_beta=calibrated[0],
                                     alpha_beta_algos=calibrated[1], **kw)
        if pri is None or cal is None:
            return None
        return float(t) - pri + cal

    inc_ms = adjusted(incumbent)
    rows: List[Dict[str, Any]] = []
    for i, r in enumerate(runner_ups or []):
        if not isinstance(r, dict):
            continue
        a = adjusted(r)
        rows.append({"index": i,
                     "strategies": r.get("strategies"),
                     "time_cost_ms": r.get("time_cost_ms"),
                     "adjusted_ms": (round(a, 6) if a is not None
                                     else None)})
    priced = [r for r in rows if r["adjusted_ms"] is not None]
    out: Dict[str, Any] = {
        "incumbent_ms": round(inc_ms, 6) if inc_ms is not None else None,
        "runner_ups": rows,
        "regret_ms": 0.0,
        "regret_frac": 0.0,
        "threshold": float(threshold),
        "triggered": False,
        "best_runner_up": None,
    }
    if inc_ms is None or not priced:
        return out
    best = min(priced, key=lambda r: r["adjusted_ms"])
    regret = max(inc_ms - best["adjusted_ms"], 0.0)
    out["best_runner_up"] = best["index"]
    out["regret_ms"] = round(regret, 6)
    out["regret_frac"] = round(regret / inc_ms, 6) if inc_ms > 0 else 0.0
    out["triggered"] = bool(regret > 0 and inc_ms > 0
                            and regret / inc_ms > threshold)
    return out


# ---------------------------------------------------------------------------
# the glue: one audit -> append, refit, sentinel
# ---------------------------------------------------------------------------


def run_calibration(
    table: Dict[str, Any],
    hpc: Any,
    model: Any,
    *,
    calibration_dir: str,
    registry: Optional[MetricsRegistry] = None,
    prior_config: Optional[str] = None,
    world: Optional[int] = None,
    device_kind: Optional[str] = None,
    min_points: int = 4,
    window_days: float = 0.0,
    max_points_per_curve: int = 0,
    regret_threshold: float = 0.05,
    plan_path: Optional[str] = None,
    mixed_precision: bool = True,
    recorder: Any = None,
    run_id: Optional[str] = None,
) -> Dict[str, Any]:
    """The whole calibration cycle off one plan-audit table: append the
    run's residual points to the store, refit the α-β curves over the
    accumulated (fingerprint-matched) points, write the calibrated
    profile, score the drift, and run the plan-regret sentinel when the
    plan carries runner-ups. Publishes ``calibration/*`` gauges and at
    most one ``plan_regret`` event into ``registry``. Never raises — it
    runs in the loop-exit ``finally`` alongside the audit; failures land
    in the returned summary's ``error``."""
    reg = registry if registry is not None else get_registry()
    out: Dict[str, Any] = {"points_appended": 0, "points_total": 0,
                           "curves_fitted": 0, "profile_path": None,
                           "drift_score": None, "regret": None}
    try:
        from hetu_galvatron_tpu.core.search_engine.profiles import (
            merge_calibrated_profile,
            read_alpha_beta,
            read_alpha_beta_algos,
            read_json,
        )
        from hetu_galvatron_tpu.observability.telemetry import (
            layer_param_mb,
        )

        fp = hardware_fingerprint(hpc, world=world,
                                  device_kind=device_kind)
        store = ResidualStore(os.path.join(calibration_dir, STORE_NAME))
        pts = calibration_points(table, hpc, model,
                                 mixed_precision=mixed_precision)
        out["points_appended"] = store.append(pts, fingerprint=fp,
                                              run_id=run_id)
        all_pts = store.load(fingerprint=fp)
        loaded = len(all_pts)
        all_pts = window_points(all_pts, window_days=window_days,
                                max_points_per_curve=max_points_per_curve)
        out["points_total"] = len(all_pts)
        out["points_windowed_out"] = loaded - len(all_pts)

        prior_cfg: Optional[Dict[str, Any]] = None
        if prior_config:
            try:
                prior_cfg = (read_json(prior_config)
                             if isinstance(prior_config, str)
                             else dict(prior_config))
            except Exception:  # noqa: BLE001 — calibrate prior-free
                prior_cfg = None

        prof, meta = refit_profile(all_pts, prior=prior_cfg,
                                   min_points=min_points)
        out["curves_fitted"] = len(meta.get("curves", {}))
        full: Optional[Dict[str, Any]] = None
        if prof:
            meta["fingerprint"] = fp
            if isinstance(prior_config, str):
                meta["prior"] = prior_config
            calibrated = dict(prof)
            calibrated[META_KEY] = meta
            full = merge_calibrated_profile(prior_cfg or {}, calibrated)
            _ensure_bandwidth_keys(full)
            out["profile_path"] = write_calibrated_profile(
                os.path.join(calibration_dir, PROFILE_NAME), full)

        ds = drift_score(table)
        out["drift_score"] = ds
        reg.gauge("calibration/points_appended").set(
            out["points_appended"])
        reg.gauge("calibration/points_total").set(out["points_total"])
        reg.gauge("calibration/curves_fitted").set(out["curves_fitted"])
        if ds is not None:
            reg.gauge("calibration/drift_score").set(round(ds, 6))
        if recorder is not None and hasattr(recorder, "retain"):
            recorder.retain("plan_audit", {
                "steps": table.get("steps"),
                "step_device_ms": table.get("step_device_ms"),
                "components": len(table.get("rows") or []),
                "drift_score": ds,
            })

        # plan-regret sentinel: needs the plan's embedded runner-ups AND
        # calibrated curves to re-price them under
        if plan_path and full is not None:
            try:
                with open(plan_path) as f:
                    plan_cfg = json.load(f)
            except (OSError, json.JSONDecodeError):
                plan_cfg = None
            rups = (plan_cfg.get("runner_ups")
                    if isinstance(plan_cfg, dict) else None)
            if isinstance(rups, list) and rups:
                incumbent = plan_spec_from_hpc(hpc)
                incumbent["time_cost_ms"] = plan_cfg.get(
                    "predicted_time_cost_ms")
                res = evaluate_plan_regret(
                    incumbent, rups,
                    seq_len=model.seq_length,
                    hidden_size=model.hidden_size,
                    param_mb=layer_param_mb(model),
                    mixed_precision=mixed_precision,
                    prior=(read_alpha_beta(prior_cfg or {}),
                           read_alpha_beta_algos(prior_cfg or {})),
                    calibrated=(read_alpha_beta(full),
                                read_alpha_beta_algos(full)),
                    threshold=regret_threshold)
                out["regret"] = res
                reg.gauge("calibration/plan_regret_ms").set(
                    res["regret_ms"])
                if res["triggered"]:
                    reg.event("plan_regret", res)
                    if recorder is not None and hasattr(recorder,
                                                        "retain"):
                        recorder.retain("plan_regret", res)
    except Exception as e:  # noqa: BLE001 — loop-exit helper, never fatal
        out["error"] = f"{type(e).__name__}: {e}"
    return out
