"""Close the loop: device-time attribution, program cost accounting, and
the predicted-vs-actual plan audit.

Everything upstream of this module *predicts*: the search engine prices a
plan with an analytical cost model, ``plan_comm_volume`` predicts what the
plan should communicate, and ``profile_alpha_beta`` fits latency/bandwidth
pairs. Nothing checked those predictions against what the hardware actually
did — the exact drift failure mode "Revisiting the Time Cost Model of
AllReduce" (PAPERS.md) documents. This module is the feedback half:

* **Trace parsing** — :func:`load_trace` reads the Chrome-trace JSON that
  ``jax.profiler.stop_trace`` writes under ``<trace_dir>/plugins/profile/
  <run>/*.trace.json.gz`` (this jax pin emits it next to the xplane proto;
  stdlib gzip+json, no tensorflow needed). Torn/corrupt captures from
  crashed runs are skipped, not fatal.
* **Attribution** — :func:`attribute` classifies device-op events into
  compute vs collective categories by HLO op-name stem (``all-reduce``,
  ``all-gather``/``reduce-scatter``, ``all-to-all``,
  ``collective-permute``), reconstructs host ``span()`` paths by interval
  containment, attributes device time to annotations that propagated onto
  device tracks (TPU; the CPU thunk trace carries ``hlo_op`` args
  instead), and measures per-track idle time — the pipeline-bubble proxy.
* **Cost accounting** — :func:`jit_cost_summary` /
  :func:`maybe_record_jit_cost` wrap ``Lowered.cost_analysis()`` (no
  backend compile — see the function docstring) so the train-step, both
  pipeline engines, and the serving prefill/decode programs publish their
  XLA-counted flops/bytes as ``cost/*`` gauges.
* **Plan audit** — :func:`audit_plan` diffs the plan's predicted
  per-component communication (``plan_comm_volume`` message sizes priced
  through the fitted α-β pairs) against the measured attribution and emits
  ``audit/*`` gauges plus one ``plan_audit`` event;
  ``cli/summarize.py`` renders it as a calibration table. This is the
  data source the topology-aware-collectives roadmap item consumes.

Known attribution limits (documented, not hidden): collective→component
mapping is by op kind, so ZeRO-3 parameter all-gathers land in the ``tp``
bucket; the HOST pipeline engine moves stage activations with
``jax.device_put`` DMAs, which never appear as ``collective-permute`` HLOs
(the compiled engine's ``ppermute`` transfers do) — its ``pp`` component
therefore measures near zero on the host path and the bubble/idle metric
carries the schedule cost instead.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import re
import weakref
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from hetu_galvatron_tpu.observability.registry import (
    MetricsRegistry,
    get_registry,
)

MB = 1024 * 1024

# ---------------------------------------------------------------------------
# trace loading (Chrome trace event format, jax.profiler output)
# ---------------------------------------------------------------------------


def latest_profile_dir(trace_dir: str) -> Optional[str]:
    """Newest ``plugins/profile/<run>`` directory under a TraceCapture
    trace_dir (run names are timestamps, so lexicographic max = newest);
    None when no capture ever flushed."""
    runs = sorted(glob.glob(os.path.join(trace_dir, "plugins", "profile",
                                         "*")))
    runs = [r for r in runs if os.path.isdir(r)]
    return runs[-1] if runs else None


@dataclass
class TraceData:
    """Merged events + track names from one profile run directory."""

    events: List[dict]
    process_names: Dict[int, str] = field(default_factory=dict)
    thread_names: Dict[Tuple[int, int], str] = field(default_factory=dict)
    path: str = ""


def load_trace(trace_dir: str) -> TraceData:
    """Parse the newest capture under ``trace_dir``. Accepts either the
    TraceCapture root (``<dir>/plugins/profile/<run>/...``) or a run
    directory itself. Unreadable/torn files are skipped — a crashed run's
    half-written capture must not kill the post-mortem."""
    run = trace_dir
    if not glob.glob(os.path.join(run, "*.trace.json*")):
        found = latest_profile_dir(trace_dir)
        if found is None:
            raise FileNotFoundError(
                f"no trace capture under {trace_dir!r} (expected "
                "plugins/profile/<run>/*.trace.json.gz)")
        run = found
    events: List[dict] = []
    procs: Dict[int, str] = {}
    threads: Dict[Tuple[int, int], str] = {}
    for path in sorted(glob.glob(os.path.join(run, "*.trace.json.gz"))
                       + glob.glob(os.path.join(run, "*.trace.json"))):
        try:
            opener = gzip.open if path.endswith(".gz") else open
            with opener(path, "rt") as f:
                obj = json.load(f)
        except (OSError, EOFError, json.JSONDecodeError, UnicodeDecodeError):
            continue
        for e in obj.get("traceEvents", []) if isinstance(obj, dict) else []:
            if not isinstance(e, dict):
                continue
            ph = e.get("ph")
            if ph == "M":
                args = e.get("args") or {}
                if e.get("name") == "process_name":
                    procs[e.get("pid")] = str(args.get("name", ""))
                elif e.get("name") == "thread_name":
                    threads[(e.get("pid"), e.get("tid"))] = str(
                        args.get("name", ""))
            elif ph == "X" and isinstance(e.get("dur"), (int, float)):
                events.append(e)
    return TraceData(events, procs, threads, run)


# ---------------------------------------------------------------------------
# event classification
# ---------------------------------------------------------------------------

# HLO op-name stems -> collective category. Async pairs
# ("all-reduce-start"/"-done") both match their stem, so their durations
# sum into the same bucket.
_COLLECTIVE_STEMS: Tuple[Tuple[str, str], ...] = (
    ("all-reduce", "allreduce"),
    ("reduce-scatter", "reducescatter"),
    ("all-gather", "allgather"),
    ("all-to-all", "alltoall"),
    ("collective-permute", "permute"),
    ("collective-broadcast", "broadcast"),
    ("send", "p2p"),
    ("recv", "p2p"),
)

# span()-style annotation names: slash-separated identifier segments
# ("train/step", "pp/fwd_s0", "layer3/attn"). HLO instruction names
# ("fusion.12", "all-reduce.1") never contain '/'.
_ANNOTATION_RE = re.compile(r"^[\w.\-]+(/[\w.\-]+)+$")
_LAYER_RE = re.compile(r"(?:^|/)layer[_]?(\d+)(?:/|$)")

# permute-source markers: the kernels stamp their collective-permutes with
# jax.named_scope metadata (ops/overlap.py TP_RING_SCOPE, ring_attention's
# cp_ring, mesh.make_pp_rotation's pp_rotate) that shows up in the trace
# event name or its tf_op/long_name args. A marked permute is billed to its
# OWN component even when tp-ring, cp-ring and pp stage rotations share one
# compiled program — the plan-level "permute -> pp iff pipelined" heuristic
# only covers whatever remains unmarked.
_PERMUTE_MARKERS: Tuple[Tuple[str, str], ...] = (
    ("tp_ring", "permute_tp"),
    ("cp_ring", "permute_cp"),
    ("pp_rotate", "permute_pp"),
    # synthesized dp gradient schedules (collectives/emit.py scopes all
    # start with dp_sched_): every hop is dp traffic
    ("dp_sched", "permute_dp"),
)
# hierarchical dp reduction markers (ops/hier_reduce.py scopes): the three
# collectives bill to the dp component — without the markers, the
# reduce-scatter/all-gather halves would land in the tp bucket (the
# Megatron-SP heuristic) on any plan that runs the hierarchical path
_HIER_MARKERS: Tuple[Tuple[str, str], ...] = (
    ("hier_dp_rs", "hier_rs"),
    ("hier_dp_ar", "hier_ar"),
    ("hier_dp_ag", "hier_ag"),
)
# device-propagated span() names whose covered permute time belongs to tp
# (the overlapped-TP step annotation, cli/train_dist.py)
_TP_SPAN = "tp/overlap_step"


def op_category(name: str) -> str:
    base = name.lower()
    for stem, cat in _COLLECTIVE_STEMS:
        if base.startswith(stem):
            return cat
    return "compute"


def _is_annotation(name: str) -> bool:
    return bool(_ANNOTATION_RE.match(name))


def _merged_busy_ms(intervals: List[Tuple[float, float]]) -> float:
    """Total covered µs->ms of possibly-overlapping (start, end) pairs."""
    if not intervals:
        return 0.0
    intervals.sort()
    busy = 0.0
    cur_s, cur_e = intervals[0]
    for s, e in intervals[1:]:
        if s > cur_e:
            busy += cur_e - cur_s
            cur_s, cur_e = s, e
        else:
            cur_e = max(cur_e, e)
    busy += cur_e - cur_s
    return busy / 1000.0


@dataclass
class Attribution:
    """Measured device-time breakdown of one captured trace window.

    Per-device quantities divide the summed device-track time by the
    number of tracks, so they compare directly against the cost model's
    per-device per-step predictions once divided by ``steps``."""

    steps: int = 0
    tracks: int = 0
    wall_ms: float = 0.0              # first-to-last device op, one track's view
    device_busy_ms: float = 0.0       # summed over tracks
    per_device_busy_ms: float = 0.0
    bubble_ms: float = 0.0            # per-device idle inside the wall window
    bubble_frac: float = 0.0
    categories_ms: Dict[str, float] = field(default_factory=dict)  # per-device
    # per-BUCKET-stage detail of the hierarchical dp reduction
    # ("hier_rs_b0", "hier_ar_b3", ... from the bucketed named scopes,
    # ops/hier_reduce.hier_stage_scope); kept OUT of categories_ms so
    # collective_ms never double-counts a marked op with its bucket row
    hier_bucket_ms: Dict[str, float] = field(default_factory=dict)
    per_module_ms: Dict[str, float] = field(default_factory=dict)  # per-device
    host_span_ms: Dict[str, float] = field(default_factory=dict)   # host wall
    device_annotation_ms: Dict[str, float] = field(default_factory=dict)
    per_layer_ms: Dict[int, float] = field(default_factory=dict)

    @property
    def collective_ms(self) -> float:
        return sum(v for k, v in self.categories_ms.items()
                   if k != "compute")

    @property
    def compute_ms(self) -> float:
        return self.categories_ms.get("compute", 0.0)


# host-span names that mark one optimizer step, tried in order: the SPMD
# trainer loop, the compiled 1F1B engine, and the host pipeline engine
# (one "pp/update" per step).
STEP_SPANS = ("train/step", "pp/compiled_step", "pp/update")


def attribute(trace: TraceData,
              step_spans: Sequence[str] = STEP_SPANS) -> Attribution:
    """Attribute the captured window. Device-op events are those carrying
    ``hlo_op``/``hlo_module`` args (CPU thunk trace) or riding a
    ``/device:*`` process (TPU tracks); annotation events are ``span()``
    names, reconstructed into nesting paths per thread by interval
    containment."""
    dev_events: List[Tuple[int, int, float, float, str, str, str]] = []
    ann_events: List[Tuple[int, int, float, float, str]] = []
    for e in trace.events:
        name = str(e.get("name", ""))
        args = e.get("args") if isinstance(e.get("args"), dict) else {}
        pid, tid = e.get("pid"), e.get("tid")
        ts, dur = float(e.get("ts", 0.0)), float(e.get("dur", 0.0))
        on_device = trace.process_names.get(pid, "").startswith("/device")
        # marker hint: the HLO metadata path (named_scope) rides in the
        # event name on some backends and in tf_op/long_name args on others
        hint = " ".join((name, str(args.get("tf_op", "")),
                         str(args.get("long_name", ""))))
        if "hlo_op" in args or "hlo_module" in args:
            dev_events.append((pid, tid, ts, dur, name,
                               str(args.get("hlo_module", "")), hint))
        elif _is_annotation(name):
            ann_events.append((pid, tid, ts, dur, name))
        elif on_device and not name.startswith(("$", "Thread")) \
                and "::" not in name:
            dev_events.append((pid, tid, ts, dur, name, "", hint))

    attr = Attribution()
    if not dev_events and not ann_events:
        return attr

    # -- device tracks: busy/idle + category + module attribution --
    by_track: Dict[Tuple[int, int],
                   List[Tuple[float, float, str, str]]] = {}
    # unmarked collective-permutes per track: candidates for the
    # tp/overlap_step annotation-coverage rebilling below
    bare_permutes: Dict[Tuple[int, int], List[Tuple[float, float]]] = {}
    cats: Dict[str, float] = {}
    hier_buckets: Dict[str, float] = {}
    mods: Dict[str, float] = {}
    for pid, tid, ts, dur, name, mod, hint in dev_events:
        by_track.setdefault((pid, tid), []).append((ts, dur, name, mod))
        cat = op_category(name)
        if cat in ("permute", "p2p", "broadcast"):
            for marker, key in _PERMUTE_MARKERS:
                if marker in hint:
                    cat = key
                    break
            else:
                if cat == "permute":
                    bare_permutes.setdefault((pid, tid), []).append(
                        (ts, ts + dur))
        elif cat in ("allgather", "reducescatter", "allreduce"):
            for marker, key in _HIER_MARKERS:
                if marker in hint:
                    cat = key
                    # bucketed schedules suffix a per-bucket stage id
                    # (hier_stage_scope "hier_dp_rs_b3"): keep the
                    # per-bucket split as DETAIL next to the base total
                    mb = re.search(re.escape(marker) + r"_b(\d+)", hint)
                    if mb is not None:
                        bk = f"{key}_b{mb.group(1)}"
                        hier_buckets[bk] = (hier_buckets.get(bk, 0.0)
                                            + dur / 1000.0)
                    break
        cats[cat] = cats.get(cat, 0.0) + dur / 1000.0
        if mod:
            mods[mod] = mods.get(mod, 0.0) + dur / 1000.0
    if by_track:
        w0 = min(ts for evs in by_track.values() for ts, _, _, _ in evs)
        w1 = max(ts + d for evs in by_track.values() for ts, d, _, _ in evs)
        attr.wall_ms = (w1 - w0) / 1000.0
        for evs in by_track.values():
            busy = _merged_busy_ms([(ts, ts + d) for ts, d, _, _ in evs])
            attr.device_busy_ms += busy
            attr.bubble_ms += max(attr.wall_ms - busy, 0.0)
        attr.tracks = len(by_track)
        attr.per_device_busy_ms = attr.device_busy_ms / attr.tracks
        attr.bubble_ms /= attr.tracks
        denom = attr.per_device_busy_ms + attr.bubble_ms
        attr.bubble_frac = attr.bubble_ms / denom if denom > 0 else 0.0
        attr.per_module_ms = {k: v / attr.tracks for k, v in mods.items()}

    # -- annotations: nesting paths (host spans) + device-track attribution
    ann_by_track: Dict[Tuple[int, int], List[Tuple[float, float, str]]] = {}
    for pid, tid, ts, dur, name in ann_events:
        ann_by_track.setdefault((pid, tid), []).append((ts, dur, name))
    # device-propagated tp/overlap_step windows per track: a bare
    # collective-permute inside one is a tp ring hop, not a stage transfer
    tp_windows: Dict[Tuple[int, int], List[Tuple[float, float]]] = {}
    # steps are counted PER TRACK and the max taken: on TPU the step
    # annotation propagates onto every device track too, so a global sum
    # would count (1 + num device tracks) per real step
    step_counts: Dict[str, Dict[Tuple[int, int], int]] = {}
    for (pid, tid), evs in ann_by_track.items():
        # containment stack: events sorted by (start, -dur) so parents
        # precede the children they cover
        evs.sort(key=lambda t: (t[0], -t[1]))
        stack: List[Tuple[float, str]] = []  # (end, path)
        on_device = trace.process_names.get(pid, "").startswith("/device")
        for ts, dur, name in evs:
            while stack and ts >= stack[-1][0] - 1e-9:
                stack.pop()
            path = (stack[-1][1] + "/" + name) if stack else name
            stack.append((ts + dur, path))
            attr.host_span_ms[path] = attr.host_span_ms.get(path, 0.0) \
                + dur / 1000.0
            if name in step_spans:
                per_track = step_counts.setdefault(name, {})
                per_track[(pid, tid)] = per_track.get((pid, tid), 0) + 1
            m = _LAYER_RE.search(name)
            if m is not None:
                attr.per_layer_ms[int(m.group(1))] = attr.per_layer_ms.get(
                    int(m.group(1)), 0.0) + dur / 1000.0
            if on_device and (pid, tid) in by_track:
                if name == _TP_SPAN:
                    tp_windows.setdefault((pid, tid), []).append(
                        (ts, ts + dur))
                # TPU device track: sum the device-op time the annotation
                # interval covers (the propagated-name attribution)
                covered = [(max(ts, ots), min(ts + dur, ots + od))
                           for ots, od, _, _ in by_track[(pid, tid)]
                           if ots < ts + dur and ots + od > ts]
                attr.device_annotation_ms[name] = \
                    attr.device_annotation_ms.get(name, 0.0) + \
                    _merged_busy_ms([c for c in covered if c[1] > c[0]])
    # rebill unmarked permute time covered by a tp/overlap_step window.
    # The span wraps the WHOLE train step (cli/train_dist.py), so this is
    # only sound when the tp ring hops are the sole collective-permutes in
    # the program — the HOST engine's case (its pp transfers are
    # device_puts, so the plan heuristic would mis-bill the rings to pp).
    # Under the COMPILED engine the pp stage rotations are in-program
    # ppermutes inside the same window: there the named_scope markers
    # above are the only sound disambiguator, and if they failed to
    # propagate, rebilling every bare permute to tp would mis-bill the
    # stage rotations — strictly worse than the plan heuristic. The
    # pp/compiled_step span (a TraceAnnotation, present even when HLO
    # metadata is stripped) is the evidence the compiled engine ran, and
    # it disables the window pass.
    compiled_pp_ran = any(name == "pp/compiled_step"
                          for _, _, _, _, name in ann_events)
    moved_us = 0.0
    for key, perms in ({} if compiled_pp_ran
                       else bare_permutes).items():
        wins = sorted(tp_windows.get(key) or [])
        if not wins:
            continue
        merged: List[Tuple[float, float]] = [wins[0]]
        for ws, we in wins[1:]:  # overlapping windows must not double-bill
            if ws <= merged[-1][1]:
                merged[-1] = (merged[-1][0], max(merged[-1][1], we))
            else:
                merged.append((ws, we))
        for ps, pe in perms:
            moved_us += sum(max(0.0, min(pe, we) - max(ps, ws))
                            for ws, we in merged)
    if moved_us:
        moved = moved_us / 1000.0
        cats["permute"] = max(cats.get("permute", 0.0) - moved, 0.0)
        cats["permute_tp"] = cats.get("permute_tp", 0.0) + moved
        if not cats["permute"]:
            cats.pop("permute", None)
    if attr.tracks:
        attr.categories_ms = {k: v / attr.tracks for k, v in cats.items()}
        attr.hier_bucket_ms = {k: v / attr.tracks
                               for k, v in hier_buckets.items()}
    for name in step_spans:  # first marker that fired wins
        if step_counts.get(name):
            attr.steps = max(step_counts[name].values())
            break
    return attr


# ---------------------------------------------------------------------------
# compiled-program cost accounting (Compiled.cost_analysis)
# ---------------------------------------------------------------------------


def jit_cost_summary(fn: Any, args: Sequence[Any] = (),
                     kwargs: Optional[Dict[str, Any]] = None
                     ) -> Dict[str, float]:
    """XLA's own static accounting for one jitted program: flops and bytes
    accessed, read from the LOWERED module (``Lowered.cost_analysis()``).
    Deliberately NO backend compile: on this jax pin an AOT
    ``.lower().compile()`` does not populate the jit dispatch cache, so
    compiling here would double every instrumented program's compile time
    (minutes for the fused 1F1B program on TPU). ``args`` may be concrete
    arrays or ``ShapeDtypeStruct``s — lowering never executes and never
    consumes donated buffers. Returns {} when the backend cannot answer
    (and never raises: this is telemetry, not the product)."""
    try:
        ca = fn.lower(*args, **(kwargs or {})).cost_analysis()
        d = ca[0] if isinstance(ca, (list, tuple)) and ca else (ca or {})
        out: Dict[str, float] = {}
        if d.get("flops"):
            out["flops"] = float(d["flops"])
        if d.get("bytes accessed"):
            out["bytes_accessed"] = float(d["bytes accessed"])
        return out
    except Exception:  # noqa: BLE001 — observability must never break a run
        return {}


# one record per (registry, program): keyed on the live registry object so
# a reused id() after GC can never suppress a fresh registry's recording
_RECORDED: "weakref.WeakKeyDictionary[MetricsRegistry, set]" = \
    weakref.WeakKeyDictionary()


def maybe_record_jit_cost(program: str, fn: Any, args: Sequence[Any] = (),
                          kwargs: Optional[Dict[str, Any]] = None,
                          registry: Optional[MetricsRegistry] = None
                          ) -> Optional[Dict[str, float]]:
    """Record one program's cost analysis as ``cost/*`` gauges (labelled
    ``program=``) plus a one-shot ``program_cost`` event — once per
    (registry, program). With no explicit registry AND no sinks configured
    this is a no-op, so un-instrumented runs pay only a set lookup."""
    reg = registry if registry is not None else get_registry()
    if registry is None and not reg.sinks:
        # only the process-default registry is sink-gated: an explicitly
        # passed registry may be scraped sink-less (the Prometheus endpoint
        # reads gauges directly), so its caller opted into the lower() cost
        return None
    seen = _RECORDED.setdefault(reg, set())
    if program in seen:
        return None
    seen.add(program)
    out = jit_cost_summary(fn, args, kwargs)
    if not out:
        return None
    for k, v in out.items():
        reg.gauge(f"cost/{k}", program=program).set(v)
    reg.event("program_cost", {"program": program, **out})
    return out


# ---------------------------------------------------------------------------
# predicted communication (plan + fitted α-β pairs)
# ---------------------------------------------------------------------------


def _ab_for(alpha_beta: Dict[str, Tuple[float, float]], size: int,
            consec: bool) -> Optional[Tuple[float, float]]:
    return (alpha_beta.get(f"{size}_{1 if consec else 0}")
            or alpha_beta.get(f"{size}_1") or alpha_beta.get(f"{size}_0"))


def _merge_algo(d: Dict[str, Any], cands: Dict[str, float],
                choices: Optional[Tuple[str, ...]] = None) -> None:
    """Accumulate per-curve candidate ms into a component dict and keep
    ``predicted_ms`` at the summed MIN choice (``choices`` restricts which
    keys compete — decomposition entries like hier_intra ride along as
    detail only)."""
    algs = d.setdefault("algorithms", {})
    for k, v in cands.items():
        algs[k] = algs.get(k, 0.0) + v
    pool = {k: v for k, v in algs.items()
            if choices is None or k in choices}
    if pool:
        best = min(pool, key=pool.get)
        d["algorithm"] = best
        d["predicted_ms"] = pool[best]


def predicted_comm_per_step(
    hpc: Any,
    model: Any,
    *,
    alpha_beta: Optional[Dict[str, Tuple[float, float]]] = None,
    alpha_beta_algos: Optional[Dict[str, Dict[str, Tuple[float, float]]]]
    = None,
    mixed_precision: bool = True,
    dcn_slices: int = 1,
) -> Dict[str, Dict[str, float]]:
    """Per component (tp/dp/sp/cp/pp): the plan's predicted per-step MB
    (``plan_comm_volume``) and — for the allreduce-derived collectives,
    when fitted α-β pairs are available — the predicted per-device ms,
    priced exactly the way the cost model prices them: one Megatron-SP
    ag/rs-equivalent message costs ``0.5 * (α + size/β)``
    (``cost_model.cost._tp_message_ms``) and a dp ring all-reduce of a
    ``size``-MB gradient buffer costs ``α + size/β`` (the curve
    ``profile_alpha_beta`` fitted). sp/cp/pp volumes are reported MB-only:
    their collectives were not fitted on the allreduce curve, so a time
    prediction here would be invented, not measured.

    The measured side (``Attribution``) is a per-device-track average, and
    each device only runs the layers of its own pipeline stage — so the
    priced times sum over all layers and divide by ``pp_deg`` (the uniform
    per-device average; volumes stay whole-plan MB).

    ``alpha_beta_algos`` (``profiles.read_alpha_beta_algos``) adds the
    PER-ALGORITHM view: each component dict gains an ``algorithms`` map of
    candidate-curve predicted ms (``flat`` plus each fitted
    ``{ring|tree}_{ici|dcn}`` curve for tp; ``flat`` / ``hier`` /
    ``hier_intra`` / ``hier_cross`` for dp when the plan runs the
    hierarchical reduction), an ``algorithm`` key naming the winner, and
    ``predicted_ms`` = the min — EXACTLY the choice the cost model priced
    (cost._tp_message_ms / cost.hier_dp_reduce_ms, called here so the two
    can never drift). ``audit_plan`` renders these as per-algorithm
    rows."""
    from hetu_galvatron_tpu.observability.telemetry import (
        layer_param_mb,
        plan_comm_volume,
    )

    chunks = max(hpc.chunks, 1)
    pp = max(getattr(hpc, "pp_deg", 1), 1)
    vols = plan_comm_volume(hpc.layers, model, global_bsz=hpc.global_bsz,
                            chunks=chunks, mixed_precision=mixed_precision)
    ab = alpha_beta or {}
    abalgos = alpha_beta_algos or {}
    param_mb = layer_param_mb(model)
    # whole-plan accumulator for the once-per-step hierarchical payload
    hier_acc = {"mb": 0.0, "dp": 1, "tp": 1}
    seq, h = model.seq_length, model.hidden_size
    elem = 2 if mixed_precision else 4
    out: Dict[str, Dict[str, float]] = {
        c: {"predicted_mb": 0.0} for c in ("tp", "dp", "sp", "cp", "pp")}
    for s, v in zip(hpc.layers, vols):
        ulysses = s.tp_size if s.sp else 1
        out["sp" if ulysses > 1 else "tp"]["predicted_mb"] += \
            v["tp_collective_mb"]
        out["dp"]["predicted_mb"] += v["dp_allreduce_mb"]
        out["cp"]["predicted_mb"] += v["cp_ring_mb"]
        out["pp"]["predicted_mb"] += v["pp_p2p_mb"]
        # α-β time predictions (allreduce-fitted collectives only)
        tp = 1 if s.sp else s.tp_size
        lbsz = max(hpc.global_bsz // chunks // max(s.dp_size, 1), 1)
        if tp > 1:
            # mirror cost._tp_message_ms EXACTLY: the search only ever
            # prices tp with the "{tp}_1" pair (tp groups are consecutive
            # by construction, level ici) and takes the MIN over the flat
            # pair and the per-algorithm ICI curves — auditing against any
            # other choice would measure drift vs a curve it never used
            act_mb = lbsz * seq * h * elem / MB
            n_msgs = 6 * chunks * (1.5 if s.checkpoint else 1.0)
            scale = n_msgs * 0.5 / pp
            cands: Dict[str, float] = {}
            pair = ab.get(f"{tp}_1")
            if pair is not None:
                cands["flat"] = (pair[0] + act_mb / pair[1]) * scale
            for alg_lvl, (alpha, beta) in (abalgos.get(f"{tp}_1") or
                                           {}).items():
                if alg_lvl.endswith("_ici"):
                    cands[alg_lvl] = (alpha + act_mb / beta) * scale
            if cands:
                # per-LAYER min summed — exactly the cost model's choice
                # (mixed curve coverage across layers stays correct: a
                # flat-only layer contributes its flat time, an
                # algo-covered layer its cheapest curve)
                out["tp"]["predicted_ms"] = out["tp"].get(
                    "predicted_ms", 0.0) + min(cands.values())
                if len(cands) > 1 or "flat" not in cands:
                    algs = out["tp"].setdefault("algorithms", {})
                    for k, v in cands.items():
                        algs[k] = algs.get(k, 0.0) + v
        sdp = max(s.dp_size * s.cp_size * ulysses, 1)
        if sdp > 1:
            cands = {}
            # dc_key convention (cost.py): tp>1 groups leave dp strided
            pair = _ab_for(ab, sdp, tp == 1)
            grad_mb = param_mb / max(tp, 1) * \
                (0.5 if mixed_precision else 1.0)
            if pair is not None:
                cands["flat"] = (pair[0] + grad_mb / pair[1]) / pp
            if getattr(hpc, "hier_dp", False):
                # the hierarchical reduction runs ONCE per step over the
                # CONCATENATED grad payload — its α must not be charged
                # per layer (unlike the flat per-buffer rings above), so
                # only the volume accumulates here; priced after the loop
                hier_acc["mb"] += grad_mb
                hier_acc["dp"] = s.dp_size
                hier_acc["tp"] = tp
            if cands:
                out["dp"]["predicted_ms"] = out["dp"].get(
                    "predicted_ms", 0.0) + cands["flat"]
                algs = out["dp"].setdefault("algorithms", {})
                algs["flat"] = algs.get("flat", 0.0) + cands["flat"]
    if hier_acc["mb"] and abalgos:
        # price the hierarchical schedule through the cost model's OWN
        # arithmetic (parity by construction): one schedule, whole-plan
        # volume, α counted once — matching both the runtime (one
        # three-collective program per step) and the summed layer costs
        # (layer_time_cost's hier_ms uses the layertype total then
        # divides by layer count)
        from hetu_galvatron_tpu.core.cost_model.cost import (
            CostContext,
            _algo_min_ms,
            _hier_dp_split,
            hier_dp_reduce_ms,
        )
        from hetu_galvatron_tpu.core.search_engine.strategies import (
            SearchStrategy,
        )

        cctx = CostContext(alpha_beta_algos=abalgos, hier_dp=True,
                           dcn_slices=dcn_slices,
                           # price the bucketed pipelined schedule the
                           # plan actually runs (0 = monolithic)
                           hier_bucket_mb=max(float(
                               getattr(hpc, "hier_bucket_mb", 0.0)), 0.0))
        ss = SearchStrategy(pp=pp, tp=hier_acc["tp"], dp=hier_acc["dp"])
        gmb = hier_acc["mb"]
        cands = {}
        hier = hier_dp_reduce_ms(ss, cctx, gmb)
        if hier is not None:
            cands["hier"] = hier / pp
            split = _hier_dp_split(ss, cctx)
            if split is not None:
                cross, intra = split
                if intra > 1:
                    cands["hier_intra"] = _algo_min_ms(
                        cctx, intra, 1, "ici", gmb) / pp
                if cross > 1:
                    ar = (_algo_min_ms(cctx, cross, 0, "dcn", gmb / intra)
                          or _algo_min_ms(cctx, cross, 1, "dcn",
                                          gmb / intra))
                    if ar is not None:
                        cands["hier_cross"] = ar / pp
        if cands:
            # hier_intra/hier_cross are the DECOMPOSITION of "hier", not
            # competing candidates — the min runs over flat/hier
            _merge_algo(out["dp"], cands, choices=("flat", "hier"))
    # prune the algorithms scaffolding when only the flat pair priced dp
    # (the legacy single-curve output shape); flag tp's accumulated
    # argmin as indicative (exact when curve coverage is layer-uniform)
    if set(out["dp"].get("algorithms", ())) == {"flat"}:
        del out["dp"]["algorithms"]
        out["dp"].pop("algorithm", None)
    tp_algs = out["tp"].get("algorithms")
    if tp_algs:
        out["tp"]["algorithm"] = min(tp_algs, key=tp_algs.get)
    return {c: d for c, d in out.items()
            if d["predicted_mb"] or d.get("predicted_ms")}


# ---------------------------------------------------------------------------
# the plan audit
# ---------------------------------------------------------------------------


def measured_components(attr: Attribution, hpc: Any) -> Dict[str, float]:
    """Map measured collective categories onto plan components using the
    plan as the disambiguator: ag/rs -> tp (Megatron-SP activations; ZeRO-3
    parameter gathers land here too — documented), a2a -> sp (Ulysses),
    allreduce -> dp when the plan has a dp/ZeRO shard group else tp (plain
    TP without SP all-reduces activations).

    Permutes are split by SOURCE first: ``attribute`` bills marked hops
    (named_scope metadata — ``tp_ring`` / ``cp_ring`` / ``pp_rotate`` —
    or, host-engine runs only, coverage by a device-propagated
    ``tp/overlap_step`` span) into ``permute_tp`` / ``permute_cp`` /
    ``permute_pp``, which map straight onto their components. Only the
    UNMARKED remainder falls back to the plan-level heuristic (pp when
    pipelined, else cp, else tp) — so a compiled program mixing tp-ring,
    cp-ring and stage-rotation permutes no longer mis-bills the ring hops
    as pipeline time."""
    cat = attr.categories_ms
    any_sdp = any(
        max(s.dp_size * s.cp_size * (s.tp_size if s.sp else 1), 1) > 1
        for s in hpc.layers)
    any_cp = any(s.cp_size > 1 for s in hpc.layers)
    permute_to = ("pp" if hpc.pp_deg > 1 else ("cp" if any_cp else "tp"))
    out: Dict[str, float] = {}

    def add(comp, ms):
        if ms:
            out[comp] = out.get(comp, 0.0) + ms

    add("tp", cat.get("allgather", 0.0) + cat.get("reducescatter", 0.0)
        + cat.get("permute_tp", 0.0))
    add("sp", cat.get("alltoall", 0.0))
    add("cp", cat.get("permute_cp", 0.0))
    add("pp", cat.get("permute_pp", 0.0))
    add("dp" if any_sdp else "tp", cat.get("allreduce", 0.0))
    # hierarchical dp reduction (marker-billed in attribute()): all three
    # collectives are dp traffic regardless of the ag/rs heuristics above
    add("dp", cat.get("hier_rs", 0.0) + cat.get("hier_ar", 0.0)
        + cat.get("hier_ag", 0.0) + cat.get("permute_dp", 0.0))
    add(permute_to, cat.get("permute", 0.0) + cat.get("p2p", 0.0)
        + cat.get("broadcast", 0.0))
    return out


def audit_plan(
    attr: Attribution,
    hpc: Any,
    model: Any,
    *,
    registry: Optional[MetricsRegistry] = None,
    alpha_beta: Optional[Dict[str, Tuple[float, float]]] = None,
    alpha_beta_algos: Optional[Dict[str, Dict[str, Tuple[float, float]]]]
    = None,
    mixed_precision: bool = True,
    predicted_layer_s: Optional[Sequence[float]] = None,
    steps: Optional[int] = None,
    dcn_slices: int = 1,
) -> Dict[str, Any]:
    """Diff the active plan's predictions against the measured attribution
    and emit the calibration data: per component, predicted MB + (α-β)
    predicted ms vs measured per-step per-device ms, the measured/predicted
    time ratio, and the α-β residual (measured − predicted, the number the
    topology-aware collective-selection work needs to know when the fitted
    curve has drifted). Also audits compute time against the cost model's
    per-layer predictions when given, and the pipeline bubble fraction
    against the 1F1B analytical ``2(pp−1)/(m+2(pp−1))``.

    With ``alpha_beta_algos``, per-ALGORITHM rows follow each priced
    component (``tp[ring_ici]``, ``dp[hier]``, ...): every candidate
    curve's predicted ms, the chosen one flagged — measured-vs-predicted
    per algorithm is exactly the signal that says whether the
    per-algorithm model beats the single curve. The hierarchical dp
    sub-collectives additionally carry their own MEASURED ms (the
    ``hier_dp_*`` scope markers bill them separately in ``attribute``).

    Emits ``audit/*`` gauges (labelled ``component=``) into ``registry``
    (the process default when omitted) plus one ``plan_audit`` event
    carrying the whole table for ``cli/summarize.py``; returns the table.
    """
    reg = registry if registry is not None else get_registry()
    n_steps = steps or attr.steps or 1
    measured = {c: ms / n_steps for c, ms in
                measured_components(attr, hpc).items()}
    predicted = predicted_comm_per_step(
        hpc, model, alpha_beta=alpha_beta,
        alpha_beta_algos=alpha_beta_algos,
        mixed_precision=mixed_precision, dcn_slices=dcn_slices)
    # measured counterparts of the hierarchical decomposition rows
    hier_measured = {
        "hier_intra": (attr.categories_ms.get("hier_rs", 0.0)
                       + attr.categories_ms.get("hier_ag", 0.0)) / n_steps,
        "hier_cross": attr.categories_ms.get("hier_ar", 0.0) / n_steps,
        "hier": (attr.categories_ms.get("hier_rs", 0.0)
                 + attr.categories_ms.get("hier_ar", 0.0)
                 + attr.categories_ms.get("hier_ag", 0.0)) / n_steps,
    }

    rows: List[Dict[str, Any]] = []
    for comp in ("tp", "dp", "sp", "cp", "pp"):
        m_ms = measured.get(comp)
        pred = predicted.get(comp, {})
        if m_ms is None and not pred:
            continue
        row: Dict[str, Any] = {"component": comp,
                               "measured_ms": round(m_ms or 0.0, 4),
                               "predicted_mb": round(
                                   pred.get("predicted_mb", 0.0), 3)}
        p_ms = pred.get("predicted_ms")
        if p_ms:
            row["predicted_ms"] = round(p_ms, 4)
            row["ratio"] = round((m_ms or 0.0) / p_ms, 4)
            row["residual_ms"] = round((m_ms or 0.0) - p_ms, 4)
        rows.append(row)
        # per-algorithm candidate rows (alpha_beta_algos present)
        chosen = pred.get("algorithm")
        for alg, alg_ms in sorted((pred.get("algorithms") or {}).items()):
            arow: Dict[str, Any] = {"component": f"{comp}[{alg}]",
                                    "predicted_ms": round(alg_ms, 4)}
            if alg == chosen:
                arow["chosen"] = True
            a_meas = (hier_measured.get(alg) if comp == "dp" else None)
            if a_meas:
                arow["measured_ms"] = round(a_meas, 4)
                if alg_ms:
                    arow["ratio"] = round(a_meas / alg_ms, 4)
                    arow["residual_ms"] = round(a_meas - alg_ms, 4)
            rows.append(arow)
        if comp == "dp" and attr.hier_bucket_ms:
            # per-bucket-stage rows (the bucketed pipelined schedule's
            # hier_dp_{rs,ar,ag}_b{i} scopes): measured-only detail under
            # the dp component — the per-bucket split is what shows
            # whether the DCN stage really hid behind the ICI stages
            _stage_rank = {"hier_rs": 0, "hier_ar": 1, "hier_ag": 2}

            def _bkey(k: str) -> Tuple[int, int, str]:
                stem, _, idx = k.rpartition("_b")
                return (int(idx), _stage_rank.get(stem, 9), stem)

            for bk in sorted(attr.hier_bucket_ms, key=_bkey):
                rows.append({"component": f"dp[{bk}]",
                             "measured_ms": round(
                                 attr.hier_bucket_ms[bk] / n_steps, 4)})

    compute_row: Dict[str, Any] = {
        "component": "compute",
        "measured_ms": round(attr.compute_ms / n_steps, 4)}
    if predicted_layer_s:
        # predicted_layer_s is per-layer SECONDS for ONE microbatch (the
        # cost model prices at lbsz = gbsz/chunks/dp; the parameter name
        # carries the unit so callers cannot pass ms by mistake). One
        # optimizer step runs `chunks` microbatches, and the measured side
        # is a per-device average where each device executes only its own
        # stage's layers — scale by chunks/pp to the same normalization.
        p = (float(sum(predicted_layer_s)) * 1000.0
             * max(hpc.chunks, 1) / max(hpc.pp_deg, 1))
        compute_row["predicted_ms"] = round(p, 4)
        if p > 0:
            compute_row["ratio"] = round(
                attr.compute_ms / n_steps / p, 4)
            compute_row["residual_ms"] = round(
                attr.compute_ms / n_steps - p, 4)
    rows.append(compute_row)

    bubble_row: Dict[str, Any] = {"component": "bubble",
                                  "measured_frac": round(attr.bubble_frac, 4)}
    if hpc.pp_deg > 1:
        m = max(hpc.chunks, 1)
        bubble_row["predicted_frac"] = round(
            2 * (hpc.pp_deg - 1) / (m + 2 * (hpc.pp_deg - 1)), 4)
    rows.append(bubble_row)

    table = {
        "steps": n_steps,
        "tracks": attr.tracks,
        "step_device_ms": round(attr.per_device_busy_ms / n_steps, 4),
        "rows": rows,
    }
    for row in rows:
        comp = row["component"]
        for key, gauge in (("measured_ms", "audit/measured_ms"),
                           ("predicted_ms", "audit/predicted_ms"),
                           ("ratio", "audit/time_ratio"),
                           ("residual_ms", "audit/residual_ms"),
                           ("predicted_mb", "audit/predicted_mb"),
                           ("measured_frac", "audit/measured_frac"),
                           ("predicted_frac", "audit/predicted_frac")):
            if key in row:
                reg.gauge(gauge, component=comp).set(row[key])
    reg.gauge("audit/step_device_ms").set(table["step_device_ms"])
    reg.event("plan_audit", table)
    return table


def analyze_and_audit(
    trace_dir: str,
    hpc: Any,
    model: Any,
    *,
    registry: Optional[MetricsRegistry] = None,
    alpha_beta: Optional[Dict[str, Tuple[float, float]]] = None,
    alpha_beta_algos: Optional[Dict[str, Dict[str, Tuple[float, float]]]]
    = None,
    mixed_precision: bool = True,
    predicted_layer_s: Optional[Sequence[float]] = None,
    step_spans: Sequence[str] = STEP_SPANS,
    dcn_slices: int = 1,
) -> Optional[Dict[str, Any]]:
    """One-call closed loop for the launchers: parse the newest capture
    under ``trace_dir``, attribute it, audit it against the plan. Thread
    per-layer per-MICROBATCH compute predictions in SECONDS via
    ``predicted_layer_s`` to get a compute-row ratio (``audit_plan`` scales
    them by chunks/pp itself) — searched plans carry them as
    ``hpc.predicted_layer_compute_ms`` (``cost_model.layer_time_components``
    fct+bct, in MILLISECONDS — divide by 1e3 before passing, as
    ``cli/train_dist.py`` does); without it the compute row is
    measured-only. Returns the audit
    table, or None when no capture/attribution is available (never raises
    — this runs in crash-path ``finally`` blocks)."""
    try:
        attr = attribute(load_trace(trace_dir), step_spans=step_spans)
        if not attr.tracks and not attr.host_span_ms:
            return None
        return audit_plan(attr, hpc, model, registry=registry,
                          alpha_beta=alpha_beta,
                          alpha_beta_algos=alpha_beta_algos,
                          mixed_precision=mixed_precision,
                          predicted_layer_s=predicted_layer_s,
                          dcn_slices=dcn_slices)
    except FileNotFoundError:
        return None
    except Exception:  # noqa: BLE001 — post-mortem helper, never fatal
        return None
