"""Prometheus text-format exposition over the metrics registry.

A stdlib-only ``/metrics`` HTTP endpoint (``http.server``, no new
dependencies) so a scraper can watch a serving process live instead of
tailing its JSONL stream. Off by default — ``serving.metrics_port`` (or a
direct :class:`MetricsHTTPServer`) turns it on; port 0 binds an ephemeral
port (tests).

Mapping to the text exposition format
(https://prometheus.io/docs/instrumenting/exposition_formats/):

* names are sanitized (``serve/ttft_ms`` -> ``serve_ttft_ms``; Prometheus
  names admit ``[a-zA-Z0-9_:]`` only),
* counters render as ``<name>_total``,
* gauges render as-is,
* histograms render the summary convention: ``<name>_count``,
  ``<name>_sum``, and ``{quantile="0.5|0.9|0.99"}`` sample lines (the
  registry keeps percentile snapshots, not buckets).

The handler snapshots under the GET, so a scrape observes a consistent
view; it never blocks the serving loop (the registry's hot path is a dict
lookup + float op, and snapshots read plain attributes).
"""

from __future__ import annotations

import json
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, Optional

from hetu_galvatron_tpu.observability.registry import (
    MetricsRegistry,
    get_registry,
)

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")
_LABEL_RE = re.compile(r"[^a-zA-Z0-9_]")


def sanitize_name(name: str) -> str:
    out = _NAME_RE.sub("_", name)
    if out and out[0].isdigit():
        out = "_" + out
    return out


def _escape(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _labels_str(labels: Dict[str, str], extra: str = "") -> str:
    parts = [f'{_LABEL_RE.sub("_", k)}="{_escape(str(v))}"'
             for k, v in sorted(labels.items())]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def prometheus_text(registry: Optional[MetricsRegistry] = None) -> str:
    """Render every metric in the registry as Prometheus text format."""
    reg = registry if registry is not None else get_registry()
    lines = []
    typed = set()

    def head(name: str, kind: str) -> None:
        if name not in typed:
            typed.add(name)
            lines.append(f"# TYPE {name} {kind}")

    for m in reg.metrics():
        name = sanitize_name(m.name)
        if m.kind == "counter":
            head(f"{name}_total", "counter")
            lines.append(f"{name}_total{_labels_str(m.labels)} {m.value}")
        elif m.kind == "gauge":
            head(name, "gauge")
            lines.append(f"{name}{_labels_str(m.labels)} {m.value}")
        elif m.kind == "histogram":
            snap = m.snapshot()
            head(name, "summary")
            for q, key in (("0.5", "p50"), ("0.9", "p90"), ("0.99", "p99")):
                quantile = 'quantile="%s"' % q
                lines.append(f"{name}{_labels_str(m.labels, quantile)} "
                             f"{snap[key]}")
            lines.append(f"{name}_sum{_labels_str(m.labels)} {m.total}")
            lines.append(f"{name}_count{_labels_str(m.labels)} {m.count}")
    return "\n".join(lines) + "\n"


class MetricsHTTPServer:
    """Background ``/metrics`` endpoint over one registry.

    ``start()`` binds (port 0 = ephemeral; the bound port is returned and
    kept in ``.port``) and serves from a daemon thread; ``stop()`` is
    idempotent. Binding failures raise at ``start()`` — a launcher that
    asked for a metrics port wants to hear the port is taken, not serve
    silently unscrapeable. The endpoint is unauthenticated, so the
    default bind is loopback-only; pass ``host="0.0.0.0"`` (or
    ``serving.metrics_host``) to expose it to an external scraper.

    ``/healthz`` answers liveness probes (load generators, k8s) with a
    tiny JSON body — 200 + uptime and last-step age — so probes never
    pay for (or depend on) the full text exposition. The serving engine
    calls :meth:`note_step` each step; ``health_fn`` lets a host process
    merge extra fields into the response (guarded: a failing hook
    reports itself instead of breaking the probe)."""

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 port: int = 0, host: str = "127.0.0.1",
                 health_fn: Optional[Callable[[], Dict[str, Any]]] = None):
        self._registry = registry
        self.host = host
        self.port = port
        self.health_fn = health_fn
        self._t_start: Optional[float] = None
        self._last_step_t: Optional[float] = None
        self._last_audit_t: Optional[float] = None
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def note_step(self) -> None:
        """Mark one unit of forward progress (engine/train step); the
        ``/healthz`` ``last_step_age_s`` field reads this."""
        self._last_step_t = time.monotonic()

    def note_audit(self) -> None:
        """Mark a completed plan-audit/calibration pass; the ``/healthz``
        ``last_audit_age_s`` field reads this (null until the first pass
        — a monitor alerting on staleness can tell "never audited" from
        "audited long ago")."""
        self._last_audit_t = time.monotonic()

    def health(self) -> Dict[str, Any]:
        now = time.monotonic()
        payload: Dict[str, Any] = {
            "status": "ok",
            "uptime_s": (now - self._t_start
                         if self._t_start is not None else 0.0),
            "last_step_age_s": (now - self._last_step_t
                                if self._last_step_t is not None else None),
            "last_audit_age_s": (
                now - self._last_audit_t
                if self._last_audit_t is not None else None),
        }
        if self.health_fn is not None:
            try:
                payload.update(self.health_fn() or {})
            except Exception as e:  # noqa: BLE001 — probe must stay alive
                payload["health_fn_error"] = f"{type(e).__name__}: {e}"
        return payload

    @property
    def registry(self) -> MetricsRegistry:
        return (self._registry if self._registry is not None
                else get_registry())

    def start(self) -> int:
        if self._httpd is not None:
            return self.port
        server = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — http.server API
                route = self.path.split("?")[0]
                if route == "/healthz":
                    body = json.dumps(server.health()).encode()
                    ctype = "application/json"
                elif route in ("/metrics", "/"):
                    body = prometheus_text(server.registry).encode()
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                else:
                    self.send_error(404)
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # scrapes must not spam stdout
                pass

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._t_start = time.monotonic()
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="metrics-http")
        self._thread.start()
        return self.port

    def stop(self) -> None:
        if self._httpd is None:
            return
        self._httpd.shutdown()
        self._httpd.server_close()
        self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False
