"""Unified training telemetry: metrics registry, trace spans, derived stats.

The measurement substrate the ROADMAP's "measurably faster" contract needs:

* :mod:`registry` — process-wide counters/gauges/histograms with labels,
  flushed to pluggable sinks (JSONL always available; TensorBoard when
  ``tensorboardX``/``torch``/``tf`` is importable, else a no-op).
* :mod:`sinks` — the sink implementations and the JSONL record schema.
* :mod:`tracing` — host-side ``span("fwd")`` context managers that also
  emit ``jax.profiler.TraceAnnotation`` so the same names show up inside
  XLA device traces, plus the windowed ``jax.profiler.start_trace`` hook.
* :mod:`telemetry` — derived training stats: tokens/sec, step-time
  percentiles, model-FLOPs utilization (FLOPs accounting lives in
  ``core/cost_model/cost.py``), device memory gauges, and per-strategy
  predicted comm volume from the plan JSON.
* :mod:`events` — per-request lifecycle event stream for the serving
  stack (submit/admit/prefill/decode/retire with a stable request id),
  written through the same sinks so ``cli/summarize.py`` can rebuild a
  timeline and a TTFT component breakdown per request.
* :mod:`recorder` — crash-forensics flight recorder: bounded ring of
  recent events + metric snapshots, dumped atomically
  (``flight_<ts>.json``) on fault/signal/NaN-halt without ever masking
  the real traceback.
* :mod:`goodput` — wall-clock partitioned into productive-step /
  checkpoint-save / restart-lost / recompile / resume-replay time,
  persisted across restarts through the checkpoint ``train_state``
  payload.

Everything here is host-side and sync-free: nothing in the hot loop calls
``float()`` on a device value (see ``TrainingTelemetry``'s lagged drain),
so attaching telemetry never serializes XLA's async dispatch.
"""

from hetu_galvatron_tpu.observability.registry import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    configure,
    get_registry,
    set_registry,
)
from hetu_galvatron_tpu.observability.sinks import (
    JsonlSink,
    NullSink,
    TensorBoardSink,
    make_tensorboard_sink,
)
from hetu_galvatron_tpu.observability.tracing import (
    TraceCapture,
    span,
)
from hetu_galvatron_tpu.observability.telemetry import (
    TrainingTelemetry,
    peak_device_tflops,
    plan_comm_volume,
)
from hetu_galvatron_tpu.observability.trace_analysis import (
    Attribution,
    analyze_and_audit,
    attribute,
    audit_plan,
    jit_cost_summary,
    load_trace,
    maybe_record_jit_cost,
)
from hetu_galvatron_tpu.observability.prometheus import (
    MetricsHTTPServer,
    prometheus_text,
)
from hetu_galvatron_tpu.observability.events import EventStream
from hetu_galvatron_tpu.observability.recorder import FlightRecorder
from hetu_galvatron_tpu.observability.goodput import GoodputTracker

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "configure",
    "get_registry",
    "set_registry",
    "JsonlSink",
    "NullSink",
    "TensorBoardSink",
    "make_tensorboard_sink",
    "TraceCapture",
    "span",
    "TrainingTelemetry",
    "peak_device_tflops",
    "plan_comm_volume",
    "Attribution",
    "analyze_and_audit",
    "attribute",
    "audit_plan",
    "jit_cost_summary",
    "load_trace",
    "maybe_record_jit_cost",
    "MetricsHTTPServer",
    "prometheus_text",
    "EventStream",
    "FlightRecorder",
    "GoodputTracker",
]
