"""Per-request lifecycle event stream (the serving half of observability).

PR 1 gave the serving stack aggregate gauges (``serve/ttft_ms`` et al.);
this module records *why* an individual request saw the latency it did: a
monotonic-clocked structured event per lifecycle transition, written as
ordinary registry events (``kind: "event"``, ``name: "request"``) through
the existing JSONL sinks, so one metrics stream carries both the
aggregates and the per-request story.

Event vocabulary (the ``ev`` field of each record; every request's
timeline starts with ``submit`` and ends with ``retire``):

=============  ==========================================================
``submit``     request entered the system (prompt_len, max_new)
``admit``      scheduler granted a slot (slot, queue_ms, cached_len,
               hit_blocks, bucket; ``cold_retry`` marks the prefix-pin
               livelock fallback — the match pinned the only evictable
               blocks, so admission retried cold)
``prefill``    prefill dispatch for the uncached suffix (bucket, suffix,
               cached, ms)
``first_token`` TTFT with its additive component split: queue_ms +
               prefill_ms + decode_ms == ttft_ms by construction
``decode``     one plain decode window emitted one token (pos)
``verify``     one speculative window (drafted, accepted; tokens emitted
               is bounded by accepted+1 but mid-window EOS/length
               retirement can cut it short — ``retire.generated`` is the
               authoritative per-request total)
``retire``     terminal transition (status, reason, generated);
               ``queued=True`` marks a request resolved before admission
=============  ==========================================================

Records carry a global monotonic sequence number (``seq``) and a
monotonic-clock millisecond timestamp (``tm``), so a timeline can be
re-assembled and its ordering *verified* after the fact
(``cli/summarize.py::request_timelines``) — orphaned or out-of-order
events are a bug the acceptance drill pins, not a rendering wart.

Cost contract: emission is host-side dict assembly + a buffered sink
write — no device values, no syncs (``analysis/lint.py`` GAL001 covers
this module). With ``enabled=False`` and no taps attached, ``emit`` is a
single attribute check; taps (the flight recorder's ring buffer) still
receive events when the sink stream is off, so a crash dump has context
even for untraced runs.
"""

from __future__ import annotations

import itertools
import time
from typing import Any, Callable, Dict, List, Optional

from hetu_galvatron_tpu.observability.registry import (
    MetricsRegistry,
    get_registry,
)

# the registry-event name every lifecycle record is filed under
REQUEST_EVENT = "request"

# terminal event: a timeline missing it is incomplete (crashed run)
TERMINAL_EV = "retire"


class EventStream:
    """Structured request-lifecycle event emitter.

    ``enabled`` gates the sink write (the JSONL stream); taps — e.g.
    :class:`~hetu_galvatron_tpu.observability.recorder.FlightRecorder`
    — always receive events, so crash forensics works even when the
    full stream is off. A tap that raises is counted
    (``tap_errors``) and skipped; event emission must never take down
    the serving loop it instruments.
    """

    def __init__(self, registry: Optional[MetricsRegistry] = None, *,
                 enabled: bool = True, name: str = REQUEST_EVENT):
        self.registry = registry if registry is not None else get_registry()
        self.enabled = bool(enabled)
        self.name = name
        self.tap_errors = 0
        self._taps: List[Callable[[str, Dict[str, Any]], None]] = []
        self._seq = itertools.count()

    def add_tap(self, fn: Callable[[str, Dict[str, Any]], None]) -> None:
        """Subscribe ``fn(name, data)`` to every emitted event (called
        synchronously, exceptions swallowed-and-counted)."""
        self._taps.append(fn)

    def emit(self, ev: str, rid: Optional[int] = None,
             **fields: Any) -> Optional[Dict[str, Any]]:
        """Record one lifecycle event; returns the data dict (or None on
        the disabled fast path). ``seq`` totally orders events within a
        stream; ``tm`` is monotonic milliseconds (duration arithmetic,
        never wall-clock)."""
        if not self.enabled and not self._taps:
            return None
        data: Dict[str, Any] = {"ev": ev, "seq": next(self._seq),
                                "tm": time.monotonic() * 1000.0}
        if rid is not None:
            data["rid"] = int(rid)
        data.update(fields)
        for tap in self._taps:
            try:
                tap(self.name, data)
            except Exception:  # noqa: BLE001 — a broken tap must not
                # break serving; the count surfaces it
                self.tap_errors += 1
        if self.enabled:
            self.registry.event(self.name, data)
        return data
