"""Metric sinks: where registry snapshots and span/event records land.

Record schema (one JSON object per line in the JSONL sink):

  {"t": <unix seconds>, "kind": "counter"|"gauge",
   "name": ..., "value": ..., "labels": {...}, "step": <int|null>}
  {"t": ..., "kind": "histogram", "name": ..., "labels": {...},
   "count": n, "mean": ..., "min": ..., "max": ...,
   "p50": ..., "p90": ..., "p99": ..., "step": ...}
  {"t": ..., "kind": "event", "name": ..., "data": {...}}

Counters/gauges carry their CURRENT value at flush time (not deltas), so
the last record per name in a file is the end-of-run value and any record
stream is trivially resumable. ``cli/summarize.py`` consumes this schema.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional


class NullSink:
    """Swallows everything; the sink CI exercises on tensorboard-less
    images."""

    def write(self, record: Dict[str, Any]) -> None:
        pass

    def flush(self) -> None:
        pass

    def close(self) -> None:
        pass


class JsonlSink:
    """Append-only JSONL file sink (always available — no deps).

    Records are buffered in memory and written on ``flush()`` so the hot
    loop never blocks on file I/O; ``close()`` flushes. The file is opened
    lazily on first flush so constructing a sink for a run that emits
    nothing leaves no artifact behind.

    Each flush lands as ONE ``os.write`` on an ``O_APPEND`` descriptor:
    when several processes (supervisor + restarted attempt, or a
    calibration sidecar) append to the same stream, their batches
    interleave at whole-flush granularity instead of mid-line — the
    reader-side contract (``cli/summarize.py`` skips unparseable lines
    with a warning) then only ever faces a torn FINAL line from a crash
    mid-write, not interior corruption.
    """

    def __init__(self, path: str):
        self.path = path
        self._buf: List[str] = []
        self._fd: Optional[int] = None

    def write(self, record: Dict[str, Any]) -> None:
        self._buf.append(json.dumps(record, separators=(",", ":"),
                                    default=_jsonable))

    def flush(self) -> None:
        if not self._buf:
            return
        if self._fd is None:
            d = os.path.dirname(os.path.abspath(self.path))
            os.makedirs(d, exist_ok=True)
            self._fd = os.open(self.path,
                               os.O_WRONLY | os.O_CREAT | os.O_APPEND,
                               0o644)
        os.write(self._fd, ("\n".join(self._buf) + "\n").encode("utf-8"))
        self._buf.clear()

    def close(self) -> None:
        self.flush()
        if self._fd is not None:
            os.close(self._fd)
            self._fd = None


def _jsonable(x):
    """Last-resort encoder: numpy / jax scalars -> python numbers."""
    try:
        return float(x)
    except (TypeError, ValueError):
        return str(x)


class TensorBoardSink:
    """Scalar forwarding to a TensorBoard event file via whichever writer
    the image has (tensorboardX, torch, or tf.summary). Use
    :func:`make_tensorboard_sink` to construct one — it degrades to
    ``None`` (caller skips the sink) when no writer library is importable
    or ``HGTPU_NO_TENSORBOARD`` is set, which is the path CI exercises."""

    def __init__(self, writer):
        self._w = writer
        self._last_step = 0

    def write(self, record: Dict[str, Any]) -> None:
        kind = record.get("kind")
        step = record.get("step")
        if step is None:
            # step-less flushes (telemetry.close() at loop exit) extend the
            # last seen step instead of stomping the chart's x=0 point
            step = self._last_step
        else:
            step = int(step)
            self._last_step = max(self._last_step, step)
        name = record.get("name", "")
        labels = record.get("labels") or {}
        if labels:
            name += "{" + ",".join(f"{k}={v}" for k, v in
                                   sorted(labels.items())) + "}"
        if kind in ("counter", "gauge"):
            self._w.add_scalar(name, float(record["value"]), step)
        elif kind == "histogram" and record.get("count"):
            for q in ("p50", "p90", "p99"):
                self._w.add_scalar(f"{name}/{q}", float(record[q]), step)

    def flush(self) -> None:
        self._w.flush()

    def close(self) -> None:
        self.flush()
        if hasattr(self._w, "close"):
            self._w.close()


class _TfScalarWriter:
    """add_scalar-shaped adapter over ``tf.summary`` (the fallback for
    images that bundle TensorFlow but neither tensorboardX nor torch)."""

    def __init__(self, logdir: str):
        import tensorflow as tf

        self._tf = tf
        self._w = tf.summary.create_file_writer(logdir)

    def add_scalar(self, name: str, value: float, step: int) -> None:
        with self._w.as_default():
            self._tf.summary.scalar(name, value, step=step)

    def flush(self) -> None:
        self._w.flush()

    def close(self) -> None:
        self._w.close()


def make_tensorboard_sink(logdir: str) -> Optional[TensorBoardSink]:
    """TensorBoardSink via whichever writer library the image has
    (tensorboardX -> torch -> tf.summary), else None.

    ``HGTPU_NO_TENSORBOARD=1`` forces the None path (how CI pins the
    no-tensorboard behaviour on images that do bundle a writer)."""
    if os.environ.get("HGTPU_NO_TENSORBOARD"):
        return None
    try:
        from tensorboardX import SummaryWriter
        return TensorBoardSink(SummaryWriter(logdir))
    except ImportError:
        pass
    try:
        from torch.utils.tensorboard import SummaryWriter
        return TensorBoardSink(SummaryWriter(logdir))
    except ImportError:
        pass
    try:
        return TensorBoardSink(_TfScalarWriter(logdir))
    except ImportError:
        return None
