"""Cross-process elastic training supervisor — the production restart
loop around ``train_dist``.

::

    python -m hetu_galvatron_tpu.cli.supervise <config.yaml> [k=v ...]

Spawns ``python -m hetu_galvatron_tpu.cli.train_dist`` with the same
config/overrides (plus ``supervisor.mode=inprocess
supervisor.auto_restart=false`` so the child never recurses, and a
per-attempt ``ckpt.load=<ckpt.save>`` once a committed checkpoint
exists) and relaunches it per the exit-code contract. Unlike the
in-process loop (``run_with_restarts``), a relaunch re-reads the fleet:
device loss/gain shows up as a world change, and a SIGKILL'd child
costs one attempt, not the run.

Exit-code contract (child -> supervisor action):

====  =====================================  =========================
code  meaning                                supervisor action
====  =====================================  =========================
0     training complete                      stop (success)
16    rerun machine: resume-to-disambiguate  restart from last commit
17    persistent validation fault / elastic  TERMINAL — restarting
      OOM rejection                          reproduces the fault
18    preempted (SIGTERM trapped, ckpt       restart from last commit
      committed at the step boundary)
130   operator SIGINT (deliberate stop)      TERMINAL — never
                                             resurrect a ^C'd run
< 0   child killed by a signal (OOM killer,  crash: restart while the
      SIGKILL mid-save, segfault)            budget lasts; surfaced
                                             terminally as 128+signum
1     unhandled exception in the child       crash: restart while the
                                             budget lasts
other (2 = argparse usage error, ...)        TERMINAL — restarting a
                                             misconfiguration only
                                             burns the budget
====  =====================================  =========================

The restart budget (``supervisor.max_restarts``) counts CONSECUTIVE
no-progress restarts: a new committed checkpoint — or a changed world,
``supervisor.max_world_changes`` times — resets it, so a long run on a
preemptible fleet survives unbounded preemptions while a crash loop
still terminates. Backoff between relaunches is full-jitter
exponential (``supervisor.backoff_base_s``/``backoff_max_s``).

Supervisor state (attempt count, budgets, last-commit receipt) persists
tmp+rename-atomically in ``supervisor.state_file`` (default
``<ckpt.save>/SUPERVISOR_STATE.json``), so a supervisor that is itself
preempted resumes with the budgets it had. Before every relaunch the
``RESUME_PIN`` lease is stamped so the child's retention GC cannot
prune the very step dir the relaunch resumes from.

Observability: the supervisor appends ``supervisor`` events to the SAME
metrics JSONL the child writes (``JsonlSink`` appends are O_APPEND +
single-``write`` atomic, so interleaving is safe), dumps a flight
record per child death when a flight dir is configured, and serves
``/healthz`` (attempt count, last child exit code, backoff state,
last-commit age = live RPO) on ``supervisor.metrics_port``.
"""

from __future__ import annotations

import os
import sys
from typing import List, Optional, Sequence

from hetu_galvatron_tpu.runtime import ckpt_paths
from hetu_galvatron_tpu.runtime.supervisor import (
    ProcessSupervisor,
    SupervisorState,
)

# overrides forced onto every child, AFTER the operator's own (later
# dotted overrides win): the child must run exactly one attempt
_CHILD_FORCED = ("supervisor.mode=inprocess",
                 "supervisor.auto_restart=false")


def _metrics_path_of(args) -> Optional[str]:
    """The same metrics-JSONL derivation train_dist's telemetry uses
    (trainer.make_telemetry), so supervisor events land in the child's
    stream."""
    obs = args.observability
    if not obs.enabled and not obs.metrics_path:
        return None
    return obs.metrics_path or os.path.join(
        args.logging.tensorboard_dir or ".", "metrics.jsonl")


def _flight_dir_of(args) -> Optional[str]:
    """Match train_dist._flight_dir_of: explicit flight_dir, else the
    metrics stream's directory — supervisor dumps sit next to child
    dumps."""
    obs = args.observability
    if obs.flight_dir is None and not obs.enabled:
        return None
    if obs.flight_dir is not None:
        return obs.flight_dir
    return os.path.dirname(os.path.abspath(
        obs.metrics_path or os.path.join(
            args.logging.tensorboard_dir or ".", "metrics.jsonl")))


def child_argv(base_argv: Sequence[str], args,
               state: SupervisorState) -> List[str]:
    """The child command line for one attempt: the operator's argv,
    then the forced single-attempt overrides, then (once a commit
    exists) the resume override — appended LAST so they win."""
    cmd = [sys.executable, "-m", "hetu_galvatron_tpu.cli.train_dist"]
    cmd.extend(base_argv)
    cmd.extend(_CHILD_FORCED)
    if args.ckpt.save and \
            ckpt_paths.latest_committed_step(args.ckpt.save) is not None:
        # resume from this run's own progress as soon as it exists — a
        # warm-start ckpt.load pointing elsewhere must not make every
        # restart retrain from the warm-start step
        cmd.append(f"ckpt.load={args.ckpt.save}")
    return cmd


def run_supervised(args, base_argv: Sequence[str]) -> int:
    """Supervise ``train_dist`` children built from ``base_argv`` until
    the run completes, turns terminal, or the budget is spent. Jax-free:
    this process must not touch the accelerator its children need."""
    sup = args.supervisor
    registry = None
    metrics_path = _metrics_path_of(args)
    if metrics_path:
        from hetu_galvatron_tpu.observability.registry import configure

        registry = configure(jsonl_path=metrics_path)
    recorder = None
    flight_dir = _flight_dir_of(args)
    if flight_dir:
        from hetu_galvatron_tpu.observability.recorder import FlightRecorder

        recorder = FlightRecorder(registry=registry, out_dir=flight_dir,
                                  prefix="flight_supervisor",
                                  capacity=args.observability.flight_events)

    supervisor = ProcessSupervisor(
        lambda state: child_argv(base_argv, args, state),
        save_dir=args.ckpt.save or None,
        state_file=sup.state_file,
        max_restarts=sup.max_restarts,
        max_world_changes=sup.max_world_changes,
        base_delay=sup.backoff_base_s,
        max_delay=sup.backoff_max_s,
        restart_on_error=sup.restart_on_error,
        term_grace_s=sup.term_grace_s,
        poll_interval=sup.poll_interval_s,
        registry=registry,
        recorder=recorder,
    )

    server = None
    if sup.metrics_port >= 0:
        from hetu_galvatron_tpu.observability.prometheus import (
            MetricsHTTPServer,
        )

        server = MetricsHTTPServer(registry=registry,
                                   port=sup.metrics_port,
                                   health_fn=supervisor.health)
        port = server.start()
        print(f"supervisor: /healthz and /metrics on "
              f"http://127.0.0.1:{port}", flush=True)
    try:
        rc = supervisor.run()
    finally:
        if server is not None:
            server.stop()
        if registry is not None:
            try:
                registry.close()
            except Exception as e:  # noqa: BLE001 — exit code is decided
                print(f"supervisor: warning: metrics close failed "
                      f"({type(e).__name__}: {e})", flush=True)
    return rc


def main(argv: Optional[Sequence[str]] = None) -> int:
    from hetu_galvatron_tpu.core.arguments import args_from_cli

    base_argv = list(argv if argv is not None else sys.argv[1:])
    args = args_from_cli(base_argv, mode="train_dist")
    return run_supervised(args, base_argv)


if __name__ == "__main__":
    sys.exit(main())
