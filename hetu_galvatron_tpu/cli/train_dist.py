"""Training launcher: ``python -m hetu_galvatron_tpu.cli.train_dist
<config.yaml> [key=value ...]``.

Capability parity with the reference launcher (models/gpt/train_dist.py:21-84):
load config -> initialize -> resolve model -> build hybrid-parallel plan ->
data iterators -> optimizer -> iteration loop with profiler/logging/
checkpoint hooks. One launcher serves every model family (the model zoo is
YAML, models/configs/*.yaml).
"""

from __future__ import annotations

import sys
from typing import Any, Dict

import numpy as np


def train(args) -> Dict[str, Any]:
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec

    from hetu_galvatron_tpu.core.profiler.runtime_profiler import RuntimeProfiler
    from hetu_galvatron_tpu.models.builder import init_causal_lm
    from hetu_galvatron_tpu.parallel.spmd import make_spmd_train_step, shard_params
    from hetu_galvatron_tpu.runtime.dataloader import get_data_iterator
    from hetu_galvatron_tpu.runtime.hybrid_config import get_hybrid_parallel_config
    from hetu_galvatron_tpu.runtime.initialize import initialize
    from hetu_galvatron_tpu.runtime.mesh import build_mesh
    from hetu_galvatron_tpu.runtime.optimizer import make_lr_schedule, make_optimizer
    from hetu_galvatron_tpu.runtime.pipeline import PipelineEngine
    from hetu_galvatron_tpu.utils.hf_config_adapter import resolve_model_config

    args = resolve_model_config(args)
    state = initialize(args)
    world = state.world_size
    hpc = get_hybrid_parallel_config(args, world)
    state.log(f"parallel plan: {hpc.describe()}")

    cfg = args.model
    params, axes = init_causal_lm(jax.random.key(args.train.seed), cfg)
    tx = make_optimizer(args.train)
    schedule = make_lr_schedule(args.train)
    data_iter = get_data_iterator(args, global_batch_size=hpc.global_bsz)
    profiler = RuntimeProfiler(args, world_size=world)

    from hetu_galvatron_tpu.models.modules import compute_dtype_of

    compute_dtype = compute_dtype_of(args.parallel.mixed_precision)
    losses = []

    if hpc.pp_deg > 1:
        eng = PipelineEngine(cfg, hpc, args.train, devices=state.devices,
                             compute_dtype=compute_dtype)
        sp = eng.split_params(params, axes)
        so = eng.init_opt(sp, axes)
        for it in range(args.train.train_iters):
            profiler.time_start(it)
            batch = next(data_iter)
            sp, so, metrics = eng.train_step(sp, so, batch)
            profiler.time_end(it)
            profiler.iteration_log(it, metrics, lr=float(schedule(it)))
            losses.append(metrics["loss"])
    else:
        mesh = build_mesh(world, 1, devices=state.devices)
        step, pspecs, ospecs, batch_shd = make_spmd_train_step(
            cfg, hpc, mesh, axes, tx, params, compute_dtype=compute_dtype)
        sp = shard_params(params, pspecs, mesh)
        so = jax.jit(tx.init, out_shardings=jax.tree.map(
            lambda s: NamedSharding(mesh, s), ospecs,
            is_leaf=lambda x: isinstance(x, PartitionSpec)))(sp)
        for it in range(args.train.train_iters):
            profiler.time_start(it)
            batch = jax.device_put(
                jax.tree.map(jnp.asarray, next(data_iter)), batch_shd)
            sp, so, metrics = step(sp, so, batch)
            profiler.time_end(it, sync=metrics["loss"])
            profiler.iteration_log(it, metrics, lr=float(schedule(it)))
            losses.append(metrics["loss"])

    losses = [float(l) for l in losses]
    if args.profile.profile:
        state.log(f"mean iter time: {profiler.filtered_time_ms():.2f} ms")
    return {"losses": losses, "iter_ms": profiler.filtered_time_ms()}


def main(argv=None) -> int:
    from hetu_galvatron_tpu.core.arguments import args_from_cli

    args = args_from_cli(argv if argv is not None else sys.argv[1:],
                         mode="train_dist")
    out = train(args)
    final = out["losses"][-1] if out["losses"] else float("nan")
    print(f"training done: {len(out['losses'])} iters, final loss {final:.4f}")
    return 0 if np.isfinite(final) else 1


if __name__ == "__main__":
    sys.exit(main())
