"""Training launcher: ``python -m hetu_galvatron_tpu.cli.train_dist
<config.yaml> [key=value ...]``.

Capability parity with the reference launcher (models/gpt/train_dist.py:21-84):
load config -> initialize -> resolve model -> build hybrid-parallel plan ->
data iterators -> optimizer -> iteration loop with profiler/logging/
checkpoint hooks. One launcher serves every model family (the model zoo is
YAML, models/configs/*.yaml).
"""

from __future__ import annotations

import sys
import time
from typing import Any, Dict

import numpy as np


def _flight_dir_of(args):
    """THE flight-dump directory resolution (explicit flight_dir, else
    the metrics stream's directory) — shared by the crash-path recorder
    construction in train() and the elastic postmortem below, so both
    kinds of dump land in the same place."""
    import os as _os

    fdir = args.observability.flight_dir
    if fdir is None:
        fdir = _os.path.dirname(_os.path.abspath(
            args.observability.metrics_path or _os.path.join(
                args.logging.tensorboard_dir or ".", "metrics.jsonl")))
    return fdir


def _flight_dump_elastic(args, reason: str, live_world: int,
                         stored_world: int, kind: str):
    """Leave a flight-recorder postmortem for a terminal elastic failure
    (rejected re-plan or reshard error — the run exits 17; the dump is
    the operator's first artifact). Returns the dump path, or None when
    no dump directory is configured/derivable. Never raises (the
    recorder's own contract)."""
    if args.observability.flight_dir is None \
            and not args.observability.enabled:
        return None
    from hetu_galvatron_tpu.observability.recorder import FlightRecorder

    rec = FlightRecorder(registry=None, out_dir=_flight_dir_of(args),
                         capacity=args.observability.flight_events)
    rec.note("elastic_replan", reason=reason, live_world=live_world,
             stored_world=stored_world, ckpt_load=args.ckpt.load)
    return rec.dump(kind)


def train(args) -> Dict[str, Any]:
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec

    from hetu_galvatron_tpu.core.profiler.runtime_profiler import RuntimeProfiler
    from hetu_galvatron_tpu.models.builder import init_causal_lm
    from hetu_galvatron_tpu.parallel.spmd import make_spmd_train_step, shard_params
    from hetu_galvatron_tpu.runtime.checkpoint import (
        CheckpointCadence,
        clear_resume_pin,
        latest_checkpoint,
        load_latest_resilient,
        save_checkpoint,
        try_read_checkpoint_meta,
    )
    from hetu_galvatron_tpu.runtime.chaos import make_chaos
    from hetu_galvatron_tpu.runtime.dataloader import (
        get_train_valid_test_data_iterators,
        skip_batches,
    )
    from hetu_galvatron_tpu.runtime.hybrid_config import get_hybrid_parallel_config
    from hetu_galvatron_tpu.runtime.initialize import initialize
    from hetu_galvatron_tpu.runtime.mesh import build_mesh
    from hetu_galvatron_tpu.runtime.optimizer import make_lr_schedule, make_optimizer
    from hetu_galvatron_tpu.runtime.pipeline import PipelineEngine
    from hetu_galvatron_tpu.runtime.rerun_machine import (
        FaultDrill,
        RerunDataIterator,
        RerunStateMachine,
    )
    from hetu_galvatron_tpu.runtime.supervisor import PreemptionGuard
    from hetu_galvatron_tpu.utils.hf_config_adapter import resolve_model_config

    args = resolve_model_config(args)

    # goodput accounting (observability/goodput.py): wall-clock
    # partitioned into productive / recompile / save / resume-replay /
    # reshard / restart-lost; snapshots ride every checkpoint's
    # train_state, so the goodput/* gauges survive preemption with the
    # model state. Constructed before the elastic pre-pass so topology
    # changes bill their re-search + reshard wall into the new bucket.
    from hetu_galvatron_tpu.observability.goodput import GoodputTracker

    goodput = GoodputTracker()

    # ----- elastic pre-pass: detect a topology-changed resume -----------
    # BEFORE initialize/plan construction: the preserved CLI plan (or the
    # checkpoint's JSON plan) describes the OLD world and may not even
    # validate on the new one. When the live world differs from the
    # checkpoint's recorded world_size, re-search a plan for the new
    # topology (cli/search_dist.py internals), gate it through the memory
    # doctor's HBM budget, and remember to reshard instead of plain-load.
    elastic = None
    if args.ckpt.load:
        from hetu_galvatron_tpu.runtime.initialize import (
            visible_world_size,
        )

        live_world = visible_world_size(args)
        ckdir0 = latest_checkpoint(args.ckpt.load)
        stored_plan = (try_read_checkpoint_meta(ckdir0)[0]
                       .get("hybrid_parallel_config") if ckdir0 else None)
        stored_world = (stored_plan or {}).get("world_size")
        if stored_world and int(stored_world) != live_world:
            from hetu_galvatron_tpu.cli.search_dist import replan_for_world
            from hetu_galvatron_tpu.runtime.rerun_machine import (
                EXIT_CODE_FAILED_ON_RESULT_VALIDATION,
            )

            print(f"elastic resume: {ckdir0} was committed by a "
                  f"{stored_world}-device world; live world is "
                  f"{live_world} — re-planning", flush=True)
            with goodput.measure("reshard"):
                reason = replan_for_world(args, live_world, stored_plan)
            if reason is not None:
                # terminal by contract: an infeasible or OOM-rejected
                # target plan reproduces on every restart — exit 17 with
                # a flight-recorder postmortem, never a restart loop
                print(f"elastic resume failed terminally: {reason}",
                      flush=True)
                dump = _flight_dump_elastic(args, reason, live_world,
                                            stored_world,
                                            "elastic_plan_rejected")
                return {"losses": [], "val_losses": [], "test_loss": None,
                        "iter_ms": 0.0, "rerun": None,
                        "goodput": {"totals": dict(goodput.totals),
                                    "frac": goodput.goodput(),
                                    "restarts_survived":
                                        goodput.restarts_survived},
                        "flight_dumps": [dump] if dump else [],
                        "exit_code": EXIT_CODE_FAILED_ON_RESULT_VALIDATION}
            elastic = {"ckdir": ckdir0, "stored_world": int(stored_world)}

    state = initialize(args)
    world = state.world_size
    hpc = get_hybrid_parallel_config(args, world)
    state.log(f"parallel plan: {hpc.describe()}")

    cfg = args.model
    params, axes = init_causal_lm(jax.random.key(args.train.seed), cfg)
    tx = make_optimizer(args.train)
    schedule = make_lr_schedule(args.train)
    base_iter, valid_iter, test_iter = get_train_valid_test_data_iterators(
        args, global_batch_size=hpc.global_bsz, hpc=hpc)
    data_iter = RerunDataIterator(base_iter)
    # unified telemetry (observability/): configures the process-wide
    # registry with JSONL (+optional TensorBoard) sinks, so the profiler's
    # histograms, the rerun machine's counters, and the derived
    # throughput/MFU stats all land in one metrics stream
    telemetry = None
    # rank-gated like the profiler's printing and TraceCapture: on a
    # multi-host pod only process 0 writes the metrics stream (every
    # process appending to one shared-storage JSONL would interleave)
    if args.observability.enabled and jax.process_index() == 0:
        from hetu_galvatron_tpu.observability.telemetry import (
            emit_plan_telemetry,
        )
        from hetu_galvatron_tpu.runtime.trainer import make_telemetry

        telemetry = make_telemetry(args, world_size=world,
                                   global_batch_size=hpc.global_bsz)
        emit_plan_telemetry(
            telemetry.registry, hpc, cfg,
            mixed_precision=args.parallel.mixed_precision != "fp32")
    # crash-forensics flight recorder (observability/recorder.py): dumps
    # flight_<ts>.json on crash / trapped signal / rerun halt. Directory:
    # observability.flight_dir, else (when telemetry owns a stream) the
    # metrics file's directory
    recorder = None
    if jax.process_index() == 0 and (telemetry is not None
                                     or args.observability.flight_dir):
        from hetu_galvatron_tpu.observability.recorder import FlightRecorder

        recorder = FlightRecorder(
            registry=(telemetry.registry if telemetry is not None
                      else None),
            out_dir=_flight_dir_of(args),
            capacity=args.observability.flight_events)
        recorder.note("run_start", plan=hpc.describe(), world=world)
    profiler = RuntimeProfiler(args, world_size=world,
                               rank=jax.process_index())
    rerun = RerunStateMachine(args.rerun)
    # preemption guard + at-step-k fault drill (runtime/supervisor.py):
    # SIGTERM/SIGINT become a checkpoint-and-exit at the next step boundary
    guard = PreemptionGuard(enabled=args.supervisor.graceful_signals,
                            recorder=recorder)
    drill = FaultDrill(args.rerun)
    # chaos fault plan (runtime/chaos.py): step-targeted crashes/signals
    # plus mid-save and retry-seam faults, one-shot across process
    # restarts via marker files next to the checkpoints
    chaos = make_chaos(args,
                       registry=(telemetry.registry if telemetry is not None
                                 else None),
                       log=state.log)
    if chaos is not None:
        chaos.install()
        state.log(f"chaos: armed faults {chaos.pending()}")
    start_iter = 0

    # overlapped-TP collectives (tp_overlap.enable, ops/overlap.py):
    # resolve per-layer eligibility once from the plan, log every fallback
    # with its reason, and remember the overlapped layer set for the
    # tp/comm_hidden_frac gauge. The rings run under BOTH pipeline
    # schedule impls: per stage submesh on the host engine, and as
    # stage-stacked shard_maps inside the compiled engine's fused program.
    tp_overlap_on = args.tp_overlap.enable
    overlapped_layers: list = []
    if tp_overlap_on:
        from hetu_galvatron_tpu.analysis.eligibility import (
            plan_overlap_reasons,
        )

        reasons = plan_overlap_reasons(cfg, hpc)
        overlapped_layers = [i for i, r in reasons if r is None]
        for i, r in reasons:
            if r is not None:
                state.log(f"tp_overlap: layer {i} falls back to GSPMD "
                          f"collectives ({r})")
        if not overlapped_layers:
            state.log("tp_overlap.enable set but no layer is eligible; "
                      "running the GSPMD path")
            tp_overlap_on = False

    # hierarchical dp/sdp gradient reduction (parallel.hier_dp or the
    # plan's "hier_dp": 1 key, ops/hier_reduce.py): resolve eligibility
    # once, log the fallback reason, remember the slice/host split
    hier_dp_on = bool(args.parallel.hier_dp or hpc.hier_dp)
    # bucketed pipelining granularity: an explicit parallel setting wins,
    # else the searched plan's recorded size (cost.hier_dp_best_bucket).
    # The RESOLVED size is written back onto hpc so every downstream
    # consumer that reads the plan (the exit audit's
    # predicted_comm_per_step prices hpc.hier_bucket_mb) sees the
    # granularity the runtime actually pipelines at, not just the plan's
    hier_bucket_mb = float(args.parallel.hier_bucket_mb
                           or hpc.hier_bucket_mb)
    hpc.hier_bucket_mb = hier_bucket_mb
    if hier_dp_on:
        from hetu_galvatron_tpu.analysis.eligibility import (
            HIER_KERNEL_REASON,
            plan_hier_dp_reason,
        )

        hier_reason = plan_hier_dp_reason(cfg, hpc)
        if hier_reason is None and tp_overlap_on:
            hier_reason = HIER_KERNEL_REASON
        if hier_reason is None and hpc.pp_deg > 1 and any(
                s.cp_size > 1 or s.sp for s in hpc.layers):
            # the pp engines keep their stage-stacked ring-cp/ulysses
            # kernels (the pp=1 SPMD path swaps them for the GSPMD core)
            hier_reason = HIER_KERNEL_REASON
        if hier_reason is None and cfg.use_flash_attn and all(
                d.platform == "tpu" for d in state.devices[:1]):
            hier_reason = HIER_KERNEL_REASON
        if hier_reason is None and cfg.use_fused_ce and world > 1:
            hier_reason = HIER_KERNEL_REASON  # vocab-parallel CE shard_map
        if hier_reason is not None:
            state.log("hier_dp: falling back to the flat GSPMD gradient "
                      f"all-reduce ({hier_reason})")
            hier_dp_on = False
        else:
            from hetu_galvatron_tpu.runtime.mesh import hier_cross_degree

            _dp = hpc.layers[0].dp_size
            _cross = hier_cross_degree(hpc.pp_deg, _dp,
                                       args.parallel.dcn_slices)
            _bkt = (f"; {hier_bucket_mb:g} MB buckets, pipelined"
                    if hier_bucket_mb > 0 else "")
            state.log("hier_dp: hierarchical gradient reduction on "
                      f"(dp {_dp} = {_cross} slice x {_dp // _cross} host;"
                      f" rs-intra / ar-cross / ag-intra, once per step{_bkt})")

    # synthesized collective schedule (collectives/): an explicit
    # parallel.dp_schedule wins, else the searched plan's recorded family
    # (engine.save_results "dp_schedule"). Only the pp=1 SPMD hier path
    # executes emitted programs; anything inexpressible falls back to the
    # hand-implemented three-stage reduction with a logged reason.
    dp_schedule_on = None
    _want_sched = str(getattr(args.parallel, "dp_schedule", "") or
                      hpc.dp_schedule or "")
    if _want_sched and hier_dp_on:
        if hpc.pp_deg > 1:
            state.log(f"dp_schedule: {_want_sched!r} needs the pp=1 SPMD "
                      "path (pp engines keep the hand-built reduction)")
        else:
            from hetu_galvatron_tpu.analysis.eligibility import (
                dp_schedule_unsupported_reason,
            )
            from hetu_galvatron_tpu.runtime.mesh import hier_cross_degree

            _dp = hpc.layers[0].dp_size
            _cross = hier_cross_degree(hpc.pp_deg, _dp,
                                       args.parallel.dcn_slices)
            _sr = dp_schedule_unsupported_reason(
                _want_sched, _dp, _cross, hier_bucket_mb)
            if _sr is not None:
                state.log(f"dp_schedule: falling back to the hand-built "
                          f"reduction ({_sr})")
            else:
                dp_schedule_on = _want_sched
                state.log(f"dp_schedule: executing the synthesized "
                          f"{_want_sched!r} program (collectives/emit.py)")
    elif _want_sched:
        state.log(f"dp_schedule: {_want_sched!r} ignored without hier_dp")

    def finish_tp_overlap_setup(step_fn):
        """Once the engine choice has settled: emit the coverage gauge and
        wrap the step in the ``tp/overlap_step`` span."""
        if not tp_overlap_on:
            return step_fn
        state.log(f"tp_overlap: {len(overlapped_layers)}/{len(hpc.layers)} "
                  "layers run decomposed ring collective matmuls")
        if telemetry is not None:
            from hetu_galvatron_tpu.observability.telemetry import (
                plan_tp_overlap_hidden_frac,
            )

            telemetry.registry.gauge("tp/comm_hidden_frac").set(
                plan_tp_overlap_hidden_frac(
                    hpc, cfg, overlapped_layers,
                    mixed_precision=args.parallel.mixed_precision != "fp32"))
        from hetu_galvatron_tpu.observability.tracing import span

        def stepped(sp_, so_, b):
            with span("tp/overlap_step"):
                return step_fn(sp_, so_, b)

        return stepped

    # batch-size ramp (reference --rampup-batch-size): the micro size
    # gbsz/chunks stays FIXED; only the microbatch count varies per step
    calc = rebatch = None
    if args.train.rampup_batch_size:
        from hetu_galvatron_tpu.runtime.microbatches import (
            MicroBatchCalculator,
            Rebatcher,
        )

        chunks0 = max(hpc.chunks, 1)
        if hpc.global_bsz % chunks0:
            raise ValueError(
                f"global_bsz {hpc.global_bsz} % chunks {chunks0} != 0")
        micro = hpc.global_bsz // chunks0
        start = int(args.train.rampup_batch_size[0])
        if start < micro and not args.train.decrease_batch_size_if_needed:
            raise ValueError(
                f"rampup start batch size {start} is below the fixed micro "
                f"size global_bsz/chunks = {micro}: the ramp varies the "
                "microbatch COUNT at a constant micro shape (XLA-static), "
                "so start must be >= global_bsz/chunks — lower chunks, "
                "raise the start, or set "
                "train.decrease_batch_size_if_needed=true to clamp")
        calc = MicroBatchCalculator(
            hpc.global_bsz, micro, 1,
            args.train.rampup_batch_size,
            args.train.decrease_batch_size_if_needed)
        state.log(
            f"batch-size ramp: start {calc.start_global_batch_size} "
            f"(running {calc.current_running_global_batch_size}) -> "
            f"{hpc.global_bsz} by {calc.batch_size_increment} over "
            f"{calc.ramp_samples} samples (micro {calc.micro_batch_size})")
        rebatch = Rebatcher(base_iter)

    from hetu_galvatron_tpu.models.modules import compute_dtype_of

    compute_dtype = compute_dtype_of(args.parallel.mixed_precision)
    losses = []
    val_losses = []
    # per-path eval fn(sp, raw_batch) -> float loss; set below once the
    # execution path (spmd / pipeline) is built
    eval_box: Dict[str, Any] = {}

    def run_eval(sp, iterator) -> float:
        vs = [eval_box["fn"](sp, next(iterator))
              for _ in range(max(args.train.eval_iters, 1))]
        return float(np.mean(vs))

    exit_code = None
    consumed_box = [0]  # ramped-run sample counter (survives maybe_resume)

    def train_state_at(step, samples, batches=None):
        """Full-state-resume payload stored in the checkpoint's meta.json:
        data-stream position (committed batches at fixed batch size —
        ``data_iter.batches_consumed``, which stays exact even after a
        geometry-changed resume — or consumed samples under a ramp), the
        RNG seed the per-step dropout keys derive from, the rerun
        machine's fault history, and the telemetry step."""
        if batches is None:
            batches = step
        ts = {"step": step, "seed": args.train.seed, "telemetry_step": step,
              "batches_consumed": batches if calc is None else None,
              "consumed_samples": samples if calc is not None else None,
              # goodput totals as of this commit + a wall stamp: the
              # resuming process books the commit-to-resume gap (dead
              # attempt's discarded work + downtime) as restart_lost
              "goodput": goodput.state_dict()}
        if rerun.enabled:
            ts["rerun"] = rerun.state_dict()
        return ts

    # one save policy for both cadences (step interval + ckpt.interval_s
    # wall cadence) and both write modes (sync/orbax-async, or the
    # on-device-snapshot writer thread when ckpt.snapshot_async) —
    # chaos mid-save faults ride the same hooks seam production uses
    cadence = CheckpointCadence(
        args.ckpt, hpc=hpc, goodput=goodput, log=state.log,
        hooks=(chaos.save_hooks() if chaos is not None else None))

    def maybe_save(it, sp, so):
        if cadence.due(it):
            cadence.save(it + 1, sp, so,
                         train_state=train_state_at(
                             it + 1, consumed_box[0],
                             batches=data_iter.batches_consumed))
            state.log(f"saved checkpoint at iter {it + 1}")

    def maybe_resume(sp, so):
        """Restore (sp, so, start_iter) and fast-forward the data stream so
        a resumed run consumes the batches an uninterrupted run would.

        Checkpoints written by this runtime carry a ``train_state`` payload
        (exact data position, seed, rerun history, telemetry step) making
        the resume step-for-step continuous. Checkpoints without it (older
        runs, converted imports) fall back to reconstructing the position
        from the step number; even when plan resharding is allowed
        (strict_plan off), the stored plan's global_bsz is compared so the
        fast-forward skips the SAMPLES the original run consumed, not
        `start` batches at the new size — preserving data order across a
        batch-size-changing resume (ADVICE r2; the reference asserts plan
        equality unconditionally)."""
        import math as _math

        nonlocal exit_code

        start = 0
        if args.ckpt.load:
            ckdir = latest_checkpoint(args.ckpt.load)
            if ckdir:
                if elastic is not None:
                    # topology-changed resume: the checkpoint's arrays are
                    # laid out for the OLD plan — gather to canonical and
                    # re-lay them onto the new engine's templates
                    # (runtime/reshard.py), billed to the reshard bucket
                    from hetu_galvatron_tpu.runtime.reshard import (
                        ReshardError,
                        resume_elastic,
                    )
                    from hetu_galvatron_tpu.runtime.rerun_machine import (
                        EXIT_CODE_FAILED_ON_RESULT_VALIDATION,
                    )

                    try:
                        with goodput.measure("reshard"):
                            sp, so, start = resume_elastic(
                                ckdir, sp, so,
                                tie_word_embeddings=cfg.tie_word_embeddings,
                                num_experts=cfg.num_experts or 0)
                    except ReshardError as e:
                        # same terminal contract as a rejected re-plan: a
                        # deterministic reshard failure reproduces on
                        # every restart — exit 17 with a postmortem, do
                        # NOT hand the supervisor a crash to loop on.
                        # start = train_iters runs zero iterations and
                        # the normal result path carries the code out.
                        state.log(f"elastic resume failed terminally: {e}")
                        if recorder is not None:
                            recorder.note(
                                "elastic_replan", reason=str(e),
                                live_world=world,
                                stored_world=elastic["stored_world"])
                            recorder.dump("elastic_reshard_failed")
                        else:
                            _flight_dump_elastic(
                                args, str(e), world,
                                elastic["stored_world"],
                                "elastic_reshard_failed")
                        exit_code = EXIT_CODE_FAILED_ON_RESULT_VALIDATION
                        return sp, so, args.train.train_iters
                    state.log(
                        f"elastic resume: resharded {ckdir} "
                        f"({elastic['stored_world']} -> {world} devices) "
                        f"onto plan [{hpc.describe()}] at iter {start}")
                else:
                    # resilient restore: a corrupted newest checkpoint
                    # (truncated meta.json, missing payload leaf, stray
                    # COMMITTED marker over a torn payload) falls back to
                    # the previous committed step with a warning, never a
                    # traceback — losing save_interval steps beats losing
                    # the run
                    with goodput.measure("resume_replay"):
                        res = load_latest_resilient(
                            args.ckpt.load, sp, so, hpc=hpc,
                            strict_plan=args.ckpt.distributed_checkpoint,
                            expected_world=world, log=state.log)
                    if res is None:
                        state.log(f"warning: {args.ckpt.load}: committed "
                                  "checkpoint vanished before resume; "
                                  "starting fresh")
                        return sp, so, 0
                    sp, so, start, ckdir = res
                    state.log(f"resumed from {ckdir} at iter {start}")
                # the supervisor's cross-process GC lease protected this
                # restore; now that the read landed, retention may proceed
                clear_resume_pin(args.ckpt.load)
                meta, meta_err = try_read_checkpoint_meta(ckdir)
                if meta_err is not None:
                    state.log(f"warning: {ckdir}/meta.json unreadable "
                              f"({meta_err}); resuming without the "
                              "train_state payload (position reconstructed "
                              "from the step number)")
                stored = meta.get("hybrid_parallel_config") or {}
                ts = meta.get("train_state") or {}
                if ts.get("goodput"):
                    # restore committed totals; the wall gap since the
                    # commit lands in restart_lost
                    goodput.load_state_dict(ts["goodput"])
                if recorder is not None:
                    recorder.note("resume", ckdir=ckdir, step=start)
                sbsz = stored.get("global_bsz")
                if ts.get("seed") not in (None, args.train.seed):
                    state.log(
                        f"warning: checkpoint seed {ts['seed']} != current "
                        f"{args.train.seed}: the replayed data stream and "
                        "dropout keys will differ from the original run")
                if ts.get("rerun") and rerun.enabled:
                    # fault history + spike EMA survive the restart, so a
                    # resume-to-disambiguate relaunch still knows the
                    # suspect iteration and thresholds stay warm
                    rerun.load_state_dict(ts["rerun"])
                if calc is not None:
                    # replay the ramp: skip exactly the samples the original
                    # run consumed over its first `start` iterations. This
                    # replays the CURRENT schedule — if the stored plan's
                    # batch geometry differs, the sample count cannot be
                    # reconstructed (the ramp triple is not in the plan
                    # fingerprint), so warn loudly instead of silently
                    # misaligning (mirrors the non-ramp branch below).
                    if (sbsz not in (None, hpc.global_bsz)
                            or stored.get("chunks") not in (None, hpc.chunks)):
                        state.log(
                            "warning: resuming a RAMPED run with a different "
                            f"batch geometry (stored global_bsz/chunks "
                            f"{sbsz}/{stored.get('chunks')} vs current "
                            f"{hpc.global_bsz}/{hpc.chunks}): the replayed "
                            "data schedule will not match the original run")
                    consumed = 0
                    for _ in range(start):
                        calc.update(consumed)
                        n = calc.current_running_global_batch_size
                        rebatch.next_batch(n)
                        consumed += n
                    if ts.get("consumed_samples") not in (None, consumed):
                        state.log(
                            f"warning: replayed ramp consumed {consumed} "
                            f"samples but the checkpoint recorded "
                            f"{ts['consumed_samples']}: the ramp schedule "
                            "changed since the original run")
                    consumed_box[0] = consumed
                    if telemetry is not None:
                        # ramped run: token accounting must use the SAMPLES
                        # actually consumed, not step * target batch size
                        telemetry.resume_from(
                            ts.get("telemetry_step", start),
                            samples=consumed)
                    return sp, so, start
                skip = ts.get("batches_consumed")
                if skip is None:
                    skip = start  # legacy checkpoint: position := step
                resumed_samples = None
                if sbsz and sbsz != hpc.global_bsz:
                    # token accounting must reflect what the ORIGINAL run
                    # consumed, not step * the new batch size
                    resumed_samples = skip * sbsz
                    skip = int(_math.ceil(skip * sbsz / hpc.global_bsz))
                    state.log(
                        f"warning: resuming a run trained at global_bsz "
                        f"{sbsz} with global_bsz {hpc.global_bsz}; "
                        f"fast-forwarding {skip} batches "
                        f"({resumed_samples} samples) to preserve data "
                        "order")
                elif stored.get("chunks") not in (None, hpc.chunks):
                    state.log(
                        f"warning: checkpoint chunks {stored.get('chunks')} "
                        f"!= current {hpc.chunks}; gradient accumulation "
                        "boundaries will differ from the original run")
                if telemetry is not None:
                    telemetry.resume_from(ts.get("telemetry_step", start),
                                          samples=resumed_samples)
                with goodput.measure("resume_replay"):
                    skip_batches(data_iter, skip)
        return sp, so, start

    use_dropout = (cfg.hidden_dropout > 0.0 or cfg.attention_dropout > 0.0)
    drop_key = jax.random.key(args.train.seed) if use_dropout else None

    def run_loop(sp, so, step_fn):
        """Shared iteration driver for both execution paths. step_fn(sp, so,
        raw_batch) -> (sp, so, metrics)."""
        nonlocal exit_code
        drill.arm(start_iter)
        consumed_prev = consumed_box[0]
        guard.__enter__()  # trap SIGTERM/SIGINT for the loop's duration
        try:
            for it in range(start_iter, args.train.train_iters):
                profiler.time_start(it)
                it_t0 = time.perf_counter()
                consumed_prev = consumed_box[0]
                if chaos is not None:
                    # fault plan fires BEFORE the update: 'crash at step
                    # k' loses exactly the steps since the last commit —
                    # the RPO the drill asserts on
                    chaos.on_step(it)
                if calc is not None:
                    if calc.update(consumed_box[0]):
                        state.log(f"ramping global batch size to "
                                  f"{calc.current_running_global_batch_size} "
                                  f"({calc.num_micro_batches} microbatches)")
                    batch = rebatch.next_batch(
                        calc.current_running_global_batch_size)
                    consumed_box[0] += calc.current_running_global_batch_size
                else:
                    batch = next(data_iter)
                if use_dropout:
                    # per-iteration rng; captured by the batch so a rerun-machine
                    # re-execution replays the SAME dropout mask (deterministic
                    # fault attribution)
                    batch = dict(batch)
                    batch["dropout_rng"] = jax.random.fold_in(drop_key, it)
                # keep pre-update state alive only when the rerun machine may
                # re-execute the step for fault attribution
                prev = (sp, so) if rerun.enabled else None
                sp, so, metrics = step_fn(sp, so, batch)
                if telemetry is not None:
                    # before any sync below: the hook's own timing must see
                    # the async cadence, and it never touches device values.
                    # During a batch-size ramp the tokens-per-step must
                    # track the RUNNING batch size, not the target
                    if calc is not None:
                        telemetry.global_batch_size = \
                            calc.current_running_global_batch_size
                    telemetry(it, metrics)
                profiler.time_end(it, sync=metrics.get("loss"))
                # goodput: the synced step wall (profiler.time_end blocks
                # on the loss). Each attempt's first iteration pays the
                # jit compile, booked as recompile, not productive;
                # checkpoint saves are measured separately below
                goodput.add(
                    "recompile" if it == start_iter else "productive_step",
                    time.perf_counter() - it_t0)
                profiler.iteration_log(it, metrics, lr=float(schedule(it)))
                # at-step-k fault drill: may corrupt the loss (nan/spike,
                # exercising the rerun machine), raise InjectedCrash, or
                # deliver a real SIGTERM the guard converts to a
                # boundary stop — all AFTER the update, BEFORE any save
                lossf = drill.apply(float(metrics["loss"]), it)
                rerun.validate_result(
                    lossf, it,
                    rerun_fn=(
                        (lambda: float(step_fn(*prev, batch)[2]["loss"]))
                        if prev is not None else None),
                    data_iterator=data_iter if calc is None else None)
                if calc is None:
                    data_iter.advance()
                losses.append(lossf)
                if (valid_iter is not None and "fn" in eval_box
                        and args.train.eval_interval
                        and (it + 1) % args.train.eval_interval == 0):
                    v = run_eval(sp, valid_iter)
                    val_losses.append({"iter": it + 1, "loss": v})
                    state.log(f"iter {it + 1}: validation loss {v:.4f} "
                              f"({args.train.eval_iters} held-out batches)")
                # check for a fault BEFORE the interval save: the faulty update
                # must never be persisted (a step_{it+1} checkpoint would shadow
                # the pre-fault step_{it} one on resume)
                exit_code = rerun.exit_code_requested()
                if exit_code is None:
                    maybe_save(it, sp, so)
                if exit_code is not None:
                    state.log(f"rerun machine requested exit (code {exit_code});"
                              " checkpointing pre-fault state")
                    if recorder is not None:
                        # NaN/validation halt: leave the postmortem (ring
                        # + metric snapshot) next to the metrics stream
                        recorder.dump(f"rerun_exit_{exit_code}")
                    if args.ckpt.save and prev is not None:
                        # save the PRE-update state at iter `it`: the faulty
                        # update must not be persisted, and the relaunch re-runs
                        # the suspect iteration to disambiguate
                        with goodput.measure("checkpoint_save"):
                            # never race an in-flight save; the drain is
                            # save time too (async saves bill their wall
                            # here, not at dispatch)
                            cadence.drain()
                            save_checkpoint(
                                args.ckpt.save, it, prev[0], prev[1],
                                hpc=hpc,
                                # position excludes the suspect iteration's
                                # batch: the relaunch must re-consume it
                                train_state=train_state_at(
                                    it, consumed_prev,
                                    batches=data_iter.batches_consumed - 1),
                                keep_last=args.ckpt.keep_last,
                                hooks=cadence.hooks)
                    break
                if guard.requested():
                    # preemption/interrupt at a step boundary: the update
                    # for iter `it` is complete, so checkpoint the
                    # POST-update state at step it+1 and exit — SIGTERM
                    # maps to restartable 18, an operator's SIGINT to
                    # non-restartable 130 (auto_restart must not resurrect
                    # a deliberately stopped run)
                    exit_code = guard.exit_code()
                    state.log("stop signal received; checkpointing "
                              f"at iter {it + 1} and exiting "
                              f"(code {exit_code})")
                    ck = args.ckpt
                    if ck.save and not (ck.save_interval and
                                        (it + 1) % ck.save_interval == 0):
                        # the interval save above did not already cover
                        # this exact step
                        with goodput.measure("checkpoint_save"):
                            cadence.drain()
                            save_checkpoint(
                                ck.save, it + 1, sp, so, hpc=hpc,
                                train_state=train_state_at(
                                    it + 1, consumed_box[0],
                                    batches=data_iter.batches_consumed),
                                keep_last=ck.keep_last,
                                hooks=cadence.hooks)
                    break
        except BaseException as e:
            # crash forensics BEFORE re-raising: the dump (ring + metric
            # snapshot + this traceback) is atomic and dump() never
            # raises, so the original fault surfaces untouched
            if recorder is not None:
                recorder.dump("crash", exc=e)
            raise
        finally:
            guard.__exit__()
            if chaos is not None:
                chaos.uninstall()
            try:
                # drain async saves even on the crash path: a supervised
                # in-process restart must never inherit live background
                # writes or stale pending commits from a dead attempt.
                # The blocking drain IS checkpoint time — async saves
                # bill their real wall here, not at dispatch
                with goodput.measure("checkpoint_save"):
                    cadence.drain()
            except Exception as e:  # noqa: BLE001 — never mask the crash
                state.log(f"warning: async checkpoint drain failed: {e}")
            # crash-safe: flush an open XLA trace window + the metrics
            # stream so both survive the exception they may help debug
            profiler.stop_trace()
            if (telemetry is not None and args.profile.trace_dir
                    and args.observability.audit):
                # close the loop: attribute the captured device trace and
                # diff it against the plan's cost-model predictions
                # (audit/* gauges + plan_audit event; flushed by the
                # telemetry close below). The whole block is guarded like
                # the checkpoint drain above: it runs on the crash path
                # too, and a post-mortem helper failing (e.g. an import
                # missing in a lean deployment) must neither mask the real
                # traceback nor skip the telemetry close.
                try:
                    from hetu_galvatron_tpu.observability.trace_analysis \
                        import analyze_and_audit

                    ab = ab_algos = None
                    if args.observability.audit_hardware_config:
                        from hetu_galvatron_tpu.core.search_engine.profiles \
                            import read_alpha_beta, read_alpha_beta_algos

                        try:
                            ab = read_alpha_beta(
                                args.observability.audit_hardware_config)
                            ab_algos = read_alpha_beta_algos(
                                args.observability.audit_hardware_config)
                        except Exception as e:  # noqa: BLE001
                            state.log(f"warning: audit_hardware_config "
                                      f"unreadable ({e}); volume-only audit")
                    # searched plans embed the cost model's per-layer
                    # compute prediction (ms); audit_plan takes SECONDS
                    pred_s = None
                    if hpc.predicted_layer_compute_ms:
                        pred_s = [v / 1e3
                                  for v in hpc.predicted_layer_compute_ms]
                    table = analyze_and_audit(
                        args.profile.trace_dir, hpc, cfg,
                        registry=telemetry.registry, alpha_beta=ab,
                        alpha_beta_algos=ab_algos,
                        mixed_precision=(
                            args.parallel.mixed_precision != "fp32"),
                        predicted_layer_s=pred_s,
                        dcn_slices=args.parallel.dcn_slices)
                    if table:
                        state.log(
                            f"plan audit: {len(table['rows'])} components "
                            f"over {table['steps']} traced step(s) — see "
                            "the plan_audit event / audit/* gauges in the "
                            "metrics stream (cli/summarize.py renders the "
                            "table)")
                    if table and args.observability.calibration_dir:
                        # close the OTHER half of the loop: feed the
                        # audit's residuals into the persistent store,
                        # re-fit the α-β curves over everything
                        # accumulated on this hardware, and run the
                        # plan-regret sentinel over the plan's embedded
                        # runner-ups (calibration/* gauges + at most one
                        # plan_regret event; never raises)
                        from hetu_galvatron_tpu.observability.calibration \
                            import run_calibration

                        cal = run_calibration(
                            table, hpc, cfg,
                            calibration_dir=(
                                args.observability.calibration_dir),
                            registry=telemetry.registry,
                            prior_config=(
                                args.observability.audit_hardware_config),
                            world=world,
                            min_points=(
                                args.observability.calibration_min_points),
                            window_days=(
                                args.observability
                                .calibration_window_days),
                            max_points_per_curve=(
                                args.observability
                                .calibration_max_points),
                            regret_threshold=(
                                args.observability.regret_threshold),
                            plan_path=(
                                args.parallel.galvatron_config_path
                                if args.parallel.config_mode == "json"
                                else None),
                            mixed_precision=(
                                args.parallel.mixed_precision != "fp32"),
                            recorder=recorder)
                        if cal.get("error"):
                            state.log("warning: calibration failed: "
                                      f"{cal['error']}")
                        else:
                            msg = (f"calibration: +{cal['points_appended']}"
                                   f" residual point(s) "
                                   f"({cal['points_total']} total), "
                                   f"{cal['curves_fitted']} curve(s) "
                                   "re-fit")
                            if cal.get("profile_path"):
                                msg += f" -> {cal['profile_path']}"
                            reg = cal.get("regret")
                            if reg and reg.get("triggered"):
                                msg += (" — PLAN REGRET: a runner-up now "
                                        "beats the incumbent by "
                                        f"{reg['regret_ms']:.3f} ms/step "
                                        "under calibrated curves "
                                        "(plan_regret event emitted)")
                            state.log(msg)
                except Exception as e:  # noqa: BLE001 — never mask the crash
                    state.log(f"warning: plan audit failed: {e}")
            if telemetry is not None:
                # export the goodput partition before the final flush so
                # the last records in the stream carry it
                goodput.flush(telemetry.registry)
                telemetry.close()
        return sp, so

    if hpc.pp_deg > 1:
        # schedule impl selection (pipeline.schedule_impl): "compiled" fuses
        # the whole 1F1B step into one SPMD program with ppermute stage
        # transfers; plans it cannot express fall back to the host-sequenced
        # engine with a logged reason (the general path)
        eng = None
        if args.pipeline.schedule_impl == "compiled":
            from hetu_galvatron_tpu.runtime.compiled_pipeline import (
                CompiledPipelineEngine,
            )

            reason = CompiledPipelineEngine.unsupported_reason(
                cfg, hpc, data=args.data)
            if reason is not None:
                state.log("pipeline.schedule_impl=compiled cannot express "
                          f"this plan ({reason}); falling back to the host "
                          "engine")
            else:
                # donation halves live model-state memory but is only safe
                # when the rerun machine never re-runs pre-update buffers.
                # tp_overlap rides INSIDE the fused program since the stage
                # axis was de-vmapped (stage-stacked shard_map kernels)
                eng = CompiledPipelineEngine(
                    cfg, hpc, args.train, devices=state.devices,
                    compute_dtype=compute_dtype,
                    dcn_slices=args.parallel.dcn_slices,
                    donate=not rerun.enabled,
                    tp_overlap=tp_overlap_on,
                    hier_dp=hier_dp_on, hier_bucket_mb=hier_bucket_mb)
                if tp_overlap_on and not eng.tp_overlap:
                    state.log("tp_overlap: no eligible layer under the "
                              f"compiled schedule ({eng.overlap_reason}); "
                              "running GSPMD collectives")
                    tp_overlap_on = False
                    overlapped_layers = []
                state.log("pipeline schedule: compiled single-program 1F1B "
                          f"(bubble_frac {eng.bubble_frac():.3f}"
                          + (", overlapped-TP rings inside"
                             if eng.tp_overlap else "") + ")")
        if eng is None:
            eng = PipelineEngine(cfg, hpc, args.train, devices=state.devices,
                                 compute_dtype=compute_dtype,
                                 dcn_slices=args.parallel.dcn_slices,
                                 tp_overlap=tp_overlap_on,
                                 hier_dp=hier_dp_on,
                                 hier_bucket_mb=hier_bucket_mb)
        sp = eng.split_params(params, axes)
        so = eng.init_opt(sp, axes)
        sp, so, start_iter = maybe_resume(sp, so)
        if valid_iter is not None or test_iter is not None:
            eval_box["fn"] = lambda sp_, raw: eng.eval_step(sp_, raw)["loss"]
        if calc is None:
            sp, so = run_loop(sp, so, finish_tp_overlap_setup(eng.train_step))
        else:
            # the stage jits are microbatch-shaped: a ramp reuses them all
            sp, so = run_loop(sp, so, finish_tp_overlap_setup(
                lambda sp_, so_, b: eng.train_step(
                    sp_, so_, b, num_microbatches=calc.num_micro_batches)))
    else:
        mesh = build_mesh(world, 1, devices=state.devices,
                          dcn_slices=args.parallel.dcn_slices)
        # donation halves live model-state memory but is only safe when the
        # rerun machine will never re-call the step on pre-update buffers
        step, pspecs, ospecs, batch_shd = make_spmd_train_step(
            cfg, hpc, mesh, axes, tx, params, compute_dtype=compute_dtype,
            donate=not rerun.enabled, tp_overlap=tp_overlap_on,
            hier_dp=hier_dp_on, dcn_slices=args.parallel.dcn_slices,
            hier_bucket_mb=hier_bucket_mb, dp_schedule=dp_schedule_on)
        nshd = jax.tree.map(
            lambda s: NamedSharding(mesh, s), ospecs,
            is_leaf=lambda x: isinstance(x, PartitionSpec))
        sp = shard_params(params, pspecs, mesh)
        so = jax.jit(tx.init, out_shardings=nshd)(sp)
        sp, so, start_iter = maybe_resume(sp, so)
        # ramp: one jitted step per distinct microbatch COUNT (micro shape
        # fixed), compiled lazily as the ramp reaches each count
        step_cache = {max(hpc.chunks, 1): step}

        def get_step(ch):
            if ch not in step_cache:
                step_cache[ch] = make_spmd_train_step(
                    cfg, hpc, mesh, axes, tx, params,
                    compute_dtype=compute_dtype,
                    donate=not rerun.enabled, chunks=ch,
                    tp_overlap=tp_overlap_on, hier_dp=hier_dp_on,
                    dcn_slices=args.parallel.dcn_slices,
                    hier_bucket_mb=hier_bucket_mb,
                    dp_schedule=dp_schedule_on)[0]
            return step_cache[ch]

        def spmd_step(sp, so, raw):
            raw = dict(raw)
            # the rng key is per-step scalar data: placed replicated, not
            # under the [B, ...] batch sharding
            rng = raw.pop("dropout_rng", None)
            b = jax.device_put(jax.tree.map(jnp.asarray, raw), batch_shd)
            if rng is not None:
                b["dropout_rng"] = rng
            fn = step if calc is None else get_step(calc.num_micro_batches)
            return fn(sp, so, b)

        if valid_iter is not None or test_iter is not None:
            from hetu_galvatron_tpu.parallel.spmd import make_spmd_eval_step

            eval_fn, eval_shd = make_spmd_eval_step(
                cfg, hpc, mesh, axes, compute_dtype=compute_dtype,
                tp_overlap=tp_overlap_on)

            def spmd_eval(sp_, raw):
                raw = dict(raw)
                raw.pop("dropout_rng", None)
                b = jax.device_put(jax.tree.map(jnp.asarray, raw), eval_shd)
                return float(eval_fn(sp_, b))

            eval_box["fn"] = spmd_eval

        sp, so = run_loop(sp, so, finish_tp_overlap_setup(spmd_step))

    with goodput.measure("checkpoint_save"):
        cadence.drain()
    test_loss = None
    if (test_iter is not None and "fn" in eval_box and exit_code is None
            and losses):
        # end-of-training held-out evaluation on the test split (the
        # reference runs evaluate() on the test iterator after training)
        test_loss = run_eval(sp, test_iter)
        state.log(f"test loss {test_loss:.4f} "
                  f"({args.train.eval_iters} held-out batches)")
    if args.profile.profile:
        state.log(f"mean iter time: {profiler.filtered_time_ms():.2f} ms")
    if rerun.enabled and rerun.records:
        state.log(f"rerun report: {rerun.report()}")
    return {"losses": losses, "val_losses": val_losses,
            "test_loss": test_loss, "iter_ms": profiler.filtered_time_ms(),
            "rerun": rerun.report() if rerun.enabled else None,
            "goodput": {"totals": dict(goodput.totals),
                        "frac": goodput.goodput(),
                        "restarts_survived": goodput.restarts_survived},
            "flight_dumps": list(recorder.dumped) if recorder else [],
            "exit_code": exit_code}


def _finish(out: Dict[str, Any]) -> int:
    if out.get("exit_code") is not None:
        return out["exit_code"]  # the reference's 16/17 fault contract
    if not out["losses"]:
        # e.g. resuming a run that had already reached train_iters
        print("training done: 0 iters (nothing left to train)")
        return 0
    final = out["losses"][-1]
    print(f"training done: {len(out['losses'])} iters, final loss {final:.4f}")
    return 0 if np.isfinite(final) else 1


def main(argv=None) -> int:
    from hetu_galvatron_tpu.core.arguments import args_from_cli

    base_argv = list(argv if argv is not None else sys.argv[1:])
    args = args_from_cli(base_argv, mode="train_dist")
    sup = args.supervisor
    if sup.auto_restart and sup.mode == "process":
        # production restart loop: delegate to the cross-process
        # supervisor (cli/supervise.py), which relaunches this module as
        # a child per attempt — exit codes, restart budget, RESUME_PIN,
        # and world changes are then real across the process boundary.
        # Nothing jax-flavored has run yet in this process, so the
        # supervisor stays off the accelerator its children need.
        from hetu_galvatron_tpu.cli.supervise import run_supervised

        return run_supervised(args, base_argv)
    if not sup.auto_restart:
        return _finish(train(args))

    # supervised mode: checkpoint-and-exit codes (16 resume-to-
    # disambiguate, 18 preempted) and crashes auto-restart with jittered
    # backoff, resuming from the last committed checkpoint; a persistent
    # validation fault (17) surfaces immediately
    from hetu_galvatron_tpu.runtime.supervisor import run_with_restarts

    last: Dict[str, Any] = {}

    from hetu_galvatron_tpu.runtime.checkpoint import latest_checkpoint

    def attempt() -> int:
        if args.ckpt.save and (not args.ckpt.load
                               or latest_checkpoint(args.ckpt.save)):
            # resume from this run's own progress as soon as it has a
            # committed checkpoint — a warm-start ckpt.load pointing
            # elsewhere must not make every restart retrain from the
            # warm-start step; until the first save lands, the original
            # load path (or a fresh start) still applies
            args.ckpt.load = args.ckpt.save
        out = train(args)
        last["out"] = out
        return out.get("exit_code") or 0

    # Within ONE process the device list is fixed at backend init, so
    # this probe observes a fleet change only when the supervisor wraps
    # relaunches across processes (drills inject it directly; a real
    # preemption kills the process, whose relaunch re-reads the fleet).
    from hetu_galvatron_tpu.runtime.initialize import visible_world_size

    rc = run_with_restarts(
        attempt, max_restarts=sup.max_restarts,
        base_delay=sup.backoff_base_s, max_delay=sup.backoff_max_s,
        restart_on_error=sup.restart_on_error,
        # the budget bounds crash LOOPS: whenever an attempt committed a
        # new checkpoint, the restart counter resets, so a long run on a
        # preemptible fleet survives unbounded preemptions
        progress_fn=((lambda: latest_checkpoint(args.ckpt.save))
                     if args.ckpt.save else None),
        # ... and a TOPOLOGY change is progress too: a restart that sees a
        # different world re-searches and reshards (the elastic pre-pass
        # in train()), so it must get a fresh budget, not inherit the old
        # world's crash count
        world_fn=lambda: visible_world_size(args))
    if rc != 0:
        return rc
    return _finish(last["out"])


if __name__ == "__main__":
    sys.exit(main())
