"""Training launcher: ``python -m hetu_galvatron_tpu.cli.train_dist
<config.yaml> [key=value ...]``.

Capability parity with the reference launcher (models/gpt/train_dist.py:21-84):
load config -> initialize -> resolve model -> build hybrid-parallel plan ->
data iterators -> optimizer -> iteration loop with profiler/logging/
checkpoint hooks. One launcher serves every model family (the model zoo is
YAML, models/configs/*.yaml).
"""

from __future__ import annotations

import sys
from typing import Any, Dict

import numpy as np


def train(args) -> Dict[str, Any]:
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec

    from hetu_galvatron_tpu.core.profiler.runtime_profiler import RuntimeProfiler
    from hetu_galvatron_tpu.models.builder import init_causal_lm
    from hetu_galvatron_tpu.parallel.spmd import make_spmd_train_step, shard_params
    from hetu_galvatron_tpu.runtime.checkpoint import (
        latest_checkpoint,
        load_checkpoint,
        save_checkpoint,
        wait_for_checkpoints,
    )
    from hetu_galvatron_tpu.runtime.dataloader import (
        get_train_valid_test_data_iterators,
    )
    from hetu_galvatron_tpu.runtime.hybrid_config import get_hybrid_parallel_config
    from hetu_galvatron_tpu.runtime.initialize import initialize
    from hetu_galvatron_tpu.runtime.mesh import build_mesh
    from hetu_galvatron_tpu.runtime.optimizer import make_lr_schedule, make_optimizer
    from hetu_galvatron_tpu.runtime.pipeline import PipelineEngine
    from hetu_galvatron_tpu.runtime.rerun_machine import (
        RerunDataIterator,
        RerunStateMachine,
    )
    from hetu_galvatron_tpu.utils.hf_config_adapter import resolve_model_config

    args = resolve_model_config(args)
    state = initialize(args)
    world = state.world_size
    hpc = get_hybrid_parallel_config(args, world)
    state.log(f"parallel plan: {hpc.describe()}")

    cfg = args.model
    params, axes = init_causal_lm(jax.random.key(args.train.seed), cfg)
    tx = make_optimizer(args.train)
    schedule = make_lr_schedule(args.train)
    base_iter, valid_iter, test_iter = get_train_valid_test_data_iterators(
        args, global_batch_size=hpc.global_bsz, hpc=hpc)
    data_iter = RerunDataIterator(base_iter)
    # unified telemetry (observability/): configures the process-wide
    # registry with JSONL (+optional TensorBoard) sinks, so the profiler's
    # histograms, the rerun machine's counters, and the derived
    # throughput/MFU stats all land in one metrics stream
    telemetry = None
    # rank-gated like the profiler's printing and TraceCapture: on a
    # multi-host pod only process 0 writes the metrics stream (every
    # process appending to one shared-storage JSONL would interleave)
    if args.observability.enabled and jax.process_index() == 0:
        from hetu_galvatron_tpu.observability.telemetry import (
            emit_plan_telemetry,
        )
        from hetu_galvatron_tpu.runtime.trainer import make_telemetry

        telemetry = make_telemetry(args, world_size=world,
                                   global_batch_size=hpc.global_bsz)
        emit_plan_telemetry(
            telemetry.registry, hpc, cfg,
            mixed_precision=args.parallel.mixed_precision != "fp32")
    profiler = RuntimeProfiler(args, world_size=world,
                               rank=jax.process_index())
    rerun = RerunStateMachine(args.rerun)
    start_iter = 0

    # batch-size ramp (reference --rampup-batch-size): the micro size
    # gbsz/chunks stays FIXED; only the microbatch count varies per step
    calc = rebatch = None
    if args.train.rampup_batch_size:
        from hetu_galvatron_tpu.runtime.microbatches import (
            MicroBatchCalculator,
            Rebatcher,
        )

        chunks0 = max(hpc.chunks, 1)
        if hpc.global_bsz % chunks0:
            raise ValueError(
                f"global_bsz {hpc.global_bsz} % chunks {chunks0} != 0")
        micro = hpc.global_bsz // chunks0
        start = int(args.train.rampup_batch_size[0])
        if start < micro and not args.train.decrease_batch_size_if_needed:
            raise ValueError(
                f"rampup start batch size {start} is below the fixed micro "
                f"size global_bsz/chunks = {micro}: the ramp varies the "
                "microbatch COUNT at a constant micro shape (XLA-static), "
                "so start must be >= global_bsz/chunks — lower chunks, "
                "raise the start, or set "
                "train.decrease_batch_size_if_needed=true to clamp")
        calc = MicroBatchCalculator(
            hpc.global_bsz, micro, 1,
            args.train.rampup_batch_size,
            args.train.decrease_batch_size_if_needed)
        state.log(
            f"batch-size ramp: start {calc.start_global_batch_size} "
            f"(running {calc.current_running_global_batch_size}) -> "
            f"{hpc.global_bsz} by {calc.batch_size_increment} over "
            f"{calc.ramp_samples} samples (micro {calc.micro_batch_size})")
        rebatch = Rebatcher(base_iter)

    from hetu_galvatron_tpu.models.modules import compute_dtype_of

    compute_dtype = compute_dtype_of(args.parallel.mixed_precision)
    losses = []
    val_losses = []
    # per-path eval fn(sp, raw_batch) -> float loss; set below once the
    # execution path (spmd / pipeline) is built
    eval_box: Dict[str, Any] = {}

    def run_eval(sp, iterator) -> float:
        vs = [eval_box["fn"](sp, next(iterator))
              for _ in range(max(args.train.eval_iters, 1))]
        return float(np.mean(vs))

    def maybe_save(it, sp, so):
        ck = args.ckpt
        if ck.save and ck.save_interval and (it + 1) % ck.save_interval == 0:
            save_checkpoint(ck.save, it + 1, sp, so, hpc=hpc,
                            async_save=ck.async_save)
            state.log(f"saved checkpoint at iter {it + 1}")

    def maybe_resume(sp, so):
        """Restore (sp, so, start_iter) and fast-forward the data stream so
        a resumed run consumes the batches an uninterrupted run would.

        Even when plan resharding is allowed (strict_plan off), the stored
        plan's global_bsz is compared so the fast-forward skips the SAMPLES
        the original run consumed, not `start` batches at the new size —
        preserving data order across a batch-size-changing resume (ADVICE
        r2; the reference asserts plan equality unconditionally)."""
        import json as _json
        import math as _math
        import os as _os

        def stored_plan(ckdir):
            mp = _os.path.join(ckdir, "meta.json")
            if not _os.path.exists(mp):
                return {}
            return _json.load(open(mp)).get("hybrid_parallel_config") or {}

        start = 0
        if args.ckpt.load:
            ckdir = latest_checkpoint(args.ckpt.load)
            if ckdir:
                sp, so, start = load_checkpoint(
                    ckdir, sp, so, hpc=hpc,
                    strict_plan=args.ckpt.distributed_checkpoint)
                state.log(f"resumed from {ckdir} at iter {start}")
                stored = stored_plan(ckdir)
                sbsz = stored.get("global_bsz")
                if calc is not None:
                    # replay the ramp: skip exactly the samples the original
                    # run consumed over its first `start` iterations. This
                    # replays the CURRENT schedule — if the stored plan's
                    # batch geometry differs, the sample count cannot be
                    # reconstructed (the ramp triple is not in the plan
                    # fingerprint), so warn loudly instead of silently
                    # misaligning (mirrors the non-ramp branch below).
                    if (sbsz not in (None, hpc.global_bsz)
                            or stored.get("chunks") not in (None, hpc.chunks)):
                        state.log(
                            "warning: resuming a RAMPED run with a different "
                            f"batch geometry (stored global_bsz/chunks "
                            f"{sbsz}/{stored.get('chunks')} vs current "
                            f"{hpc.global_bsz}/{hpc.chunks}): the replayed "
                            "data schedule will not match the original run")
                    consumed = 0
                    for _ in range(start):
                        calc.update(consumed)
                        n = calc.current_running_global_batch_size
                        rebatch.next_batch(n)
                        consumed += n
                    consumed_box[0] = consumed
                    return sp, so, start
                skip = start
                if sbsz and sbsz != hpc.global_bsz:
                    skip = int(_math.ceil(start * sbsz / hpc.global_bsz))
                    state.log(
                        f"warning: resuming a run trained at global_bsz "
                        f"{sbsz} with global_bsz {hpc.global_bsz}; "
                        f"fast-forwarding {skip} batches "
                        f"({start * sbsz} samples) to preserve data order")
                elif stored.get("chunks") not in (None, hpc.chunks):
                    state.log(
                        f"warning: checkpoint chunks {stored.get('chunks')} "
                        f"!= current {hpc.chunks}; gradient accumulation "
                        "boundaries will differ from the original run")
                for _ in range(skip):
                    next(data_iter)
                    data_iter.advance()
        return sp, so, start

    exit_code = None
    consumed_box = [0]  # ramped-run sample counter (survives maybe_resume)

    use_dropout = (cfg.hidden_dropout > 0.0 or cfg.attention_dropout > 0.0)
    drop_key = jax.random.key(args.train.seed) if use_dropout else None

    def run_loop(sp, so, step_fn):
        """Shared iteration driver for both execution paths. step_fn(sp, so,
        raw_batch) -> (sp, so, metrics)."""
        nonlocal exit_code
        try:
            for it in range(start_iter, args.train.train_iters):
                profiler.time_start(it)
                if calc is not None:
                    if calc.update(consumed_box[0]):
                        state.log(f"ramping global batch size to "
                                  f"{calc.current_running_global_batch_size} "
                                  f"({calc.num_micro_batches} microbatches)")
                    batch = rebatch.next_batch(
                        calc.current_running_global_batch_size)
                    consumed_box[0] += calc.current_running_global_batch_size
                else:
                    batch = next(data_iter)
                if use_dropout:
                    # per-iteration rng; captured by the batch so a rerun-machine
                    # re-execution replays the SAME dropout mask (deterministic
                    # fault attribution)
                    batch = dict(batch)
                    batch["dropout_rng"] = jax.random.fold_in(drop_key, it)
                # keep pre-update state alive only when the rerun machine may
                # re-execute the step for fault attribution
                prev = (sp, so) if rerun.enabled else None
                sp, so, metrics = step_fn(sp, so, batch)
                if telemetry is not None:
                    # before any sync below: the hook's own timing must see
                    # the async cadence, and it never touches device values.
                    # During a batch-size ramp the tokens-per-step must
                    # track the RUNNING batch size, not the target
                    if calc is not None:
                        telemetry.global_batch_size = \
                            calc.current_running_global_batch_size
                    telemetry(it, metrics)
                profiler.time_end(it, sync=metrics.get("loss"))
                profiler.iteration_log(it, metrics, lr=float(schedule(it)))
                rerun.validate_result(
                    float(metrics["loss"]), it,
                    rerun_fn=(
                        (lambda: float(step_fn(*prev, batch)[2]["loss"]))
                        if prev is not None else None),
                    data_iterator=data_iter if calc is None else None)
                if calc is None:
                    data_iter.advance()
                losses.append(float(metrics["loss"]))
                if (valid_iter is not None and "fn" in eval_box
                        and args.train.eval_interval
                        and (it + 1) % args.train.eval_interval == 0):
                    v = run_eval(sp, valid_iter)
                    val_losses.append({"iter": it + 1, "loss": v})
                    state.log(f"iter {it + 1}: validation loss {v:.4f} "
                              f"({args.train.eval_iters} held-out batches)")
                # check for a fault BEFORE the interval save: the faulty update
                # must never be persisted (a step_{it+1} checkpoint would shadow
                # the pre-fault step_{it} one on resume)
                exit_code = rerun.exit_code_requested()
                if exit_code is None:
                    maybe_save(it, sp, so)
                if exit_code is not None:
                    state.log(f"rerun machine requested exit (code {exit_code});"
                              " checkpointing pre-fault state")
                    if args.ckpt.save and prev is not None:
                        # save the PRE-update state at iter `it`: the faulty
                        # update must not be persisted, and the relaunch re-runs
                        # the suspect iteration to disambiguate
                        wait_for_checkpoints()  # never race an in-flight save
                        save_checkpoint(args.ckpt.save, it, prev[0], prev[1],
                                        hpc=hpc)
                    break
        finally:
            # crash-safe: flush an open XLA trace window + the metrics
            # stream so both survive the exception they may help debug
            profiler.stop_trace()
            if telemetry is not None:
                telemetry.close()
        return sp, so

    if hpc.pp_deg > 1:
        eng = PipelineEngine(cfg, hpc, args.train, devices=state.devices,
                             compute_dtype=compute_dtype,
                             dcn_slices=args.parallel.dcn_slices)
        sp = eng.split_params(params, axes)
        so = eng.init_opt(sp, axes)
        sp, so, start_iter = maybe_resume(sp, so)
        if valid_iter is not None or test_iter is not None:
            eval_box["fn"] = lambda sp_, raw: eng.eval_step(sp_, raw)["loss"]
        if calc is None:
            sp, so = run_loop(sp, so, eng.train_step)
        else:
            # the stage jits are microbatch-shaped: a ramp reuses them all
            sp, so = run_loop(sp, so, lambda sp_, so_, b: eng.train_step(
                sp_, so_, b, num_microbatches=calc.num_micro_batches))
    else:
        mesh = build_mesh(world, 1, devices=state.devices,
                          dcn_slices=args.parallel.dcn_slices)
        # donation halves live model-state memory but is only safe when the
        # rerun machine will never re-call the step on pre-update buffers
        step, pspecs, ospecs, batch_shd = make_spmd_train_step(
            cfg, hpc, mesh, axes, tx, params, compute_dtype=compute_dtype,
            donate=not rerun.enabled)
        nshd = jax.tree.map(
            lambda s: NamedSharding(mesh, s), ospecs,
            is_leaf=lambda x: isinstance(x, PartitionSpec))
        sp = shard_params(params, pspecs, mesh)
        so = jax.jit(tx.init, out_shardings=nshd)(sp)
        sp, so, start_iter = maybe_resume(sp, so)
        # ramp: one jitted step per distinct microbatch COUNT (micro shape
        # fixed), compiled lazily as the ramp reaches each count
        step_cache = {max(hpc.chunks, 1): step}

        def get_step(ch):
            if ch not in step_cache:
                step_cache[ch] = make_spmd_train_step(
                    cfg, hpc, mesh, axes, tx, params,
                    compute_dtype=compute_dtype,
                    donate=not rerun.enabled, chunks=ch)[0]
            return step_cache[ch]

        def spmd_step(sp, so, raw):
            raw = dict(raw)
            # the rng key is per-step scalar data: placed replicated, not
            # under the [B, ...] batch sharding
            rng = raw.pop("dropout_rng", None)
            b = jax.device_put(jax.tree.map(jnp.asarray, raw), batch_shd)
            if rng is not None:
                b["dropout_rng"] = rng
            fn = step if calc is None else get_step(calc.num_micro_batches)
            return fn(sp, so, b)

        if valid_iter is not None or test_iter is not None:
            from hetu_galvatron_tpu.parallel.spmd import make_spmd_eval_step

            eval_fn, eval_shd = make_spmd_eval_step(
                cfg, hpc, mesh, axes, compute_dtype=compute_dtype)

            def spmd_eval(sp_, raw):
                raw = dict(raw)
                raw.pop("dropout_rng", None)
                b = jax.device_put(jax.tree.map(jnp.asarray, raw), eval_shd)
                return float(eval_fn(sp_, b))

            eval_box["fn"] = spmd_eval

        sp, so = run_loop(sp, so, spmd_step)

    wait_for_checkpoints()
    test_loss = None
    if (test_iter is not None and "fn" in eval_box and exit_code is None
            and losses):
        # end-of-training held-out evaluation on the test split (the
        # reference runs evaluate() on the test iterator after training)
        test_loss = run_eval(sp, test_iter)
        state.log(f"test loss {test_loss:.4f} "
                  f"({args.train.eval_iters} held-out batches)")
    if args.profile.profile:
        state.log(f"mean iter time: {profiler.filtered_time_ms():.2f} ms")
    if rerun.enabled and rerun.records:
        state.log(f"rerun report: {rerun.report()}")
    return {"losses": losses, "val_losses": val_losses,
            "test_loss": test_loss, "iter_ms": profiler.filtered_time_ms(),
            "rerun": rerun.report() if rerun.enabled else None,
            "exit_code": exit_code}


def main(argv=None) -> int:
    from hetu_galvatron_tpu.core.arguments import args_from_cli

    args = args_from_cli(argv if argv is not None else sys.argv[1:],
                         mode="train_dist")
    out = train(args)
    if out.get("exit_code") is not None:
        return out["exit_code"]  # the reference's 16/17 fault contract
    if not out["losses"]:
        # e.g. resuming a run that had already reached train_iters
        print("training done: 0 iters (nothing left to train)")
        return 0
    final = out["losses"][-1]
    print(f"training done: {len(out['losses'])} iters, final loss {final:.4f}")
    return 0 if np.isfinite(final) else 1


if __name__ == "__main__":
    sys.exit(main())
