"""Static checks CLI: ``python -m hetu_galvatron_tpu.cli.check``.

Run the three-pass static analysis suite (``analysis/``) on CPU — no TPU,
no training step — BEFORE burning accelerator time:

* ``--plan plan.json [--model cfg.yaml] [--world N]`` — Pass 1, the plan
  doctor: per-layer engine/kernel report with actionable errors for
  malformed plans.
* ``--census`` — Pass 2: trace the compiled 1F1B step for the committed
  acceptance plan plus the serving prefill/decode programs, census their
  collectives, verify named_scope marker coverage and the exact-count
  cross-check against the plan arithmetic
  (``telemetry.plan_collective_counts``).
* ``--lint [--update-baseline]`` — Pass 3: the AST lint with the
  committed baseline (``analysis/lint_baseline.json``); the gate is zero
  NEW findings.
* ``--all`` — every pass: the plan doctor over the committed example
  plans, the census smoke, and the lint gate. This is the CI step
  (``__graft_entry__.dryrun_multichip`` runs it and tier-1 asserts it
  green).

Exit code 0 = clean, 1 = findings/errors, 2 = usage.
"""

from __future__ import annotations

import argparse
import glob
import os
import sys
from typing import List, Optional

EXAMPLE_PLAN_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "profiles", "example_plans")
ACCEPTANCE_PLAN = os.path.join(
    EXAMPLE_PLAN_DIR, "galvatron_config_acceptance_tp2dp2pp2.json")


def _force_cpu_devices(n: int = 8) -> None:
    """Static analysis must run on CPU with no accelerator: force the
    virtual host platform BEFORE jax initializes (a no-op when the test
    harness already did). APPEND to any pre-existing XLA_FLAGS — a host
    exporting e.g. --xla_dump_to must not silently lose the device-count
    flag (the tools/pipeline_dispatch_bench.py pattern)."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (flags + " " if flags else "") + \
            f"--xla_force_host_platform_device_count={n}"
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")


def _example_model():
    """The tiny 4-layer model the committed example plans were written
    for (the dryrun/test shape: every kernel family exercisable on the
    8-device virtual mesh)."""
    from hetu_galvatron_tpu.core.args_schema import CoreArgs

    return CoreArgs.model_validate({
        "model": {
            "hidden_size": 64, "num_hidden_layers": 4,
            "num_attention_heads": 4, "vocab_size": 256,
            "seq_length": 16, "max_position_embeddings": 32,
            "hidden_act": "swiglu", "normalization": "rmsnorm",
            "position_embedding_type": "rope", "tie_word_embeddings": False,
            "add_bias_linear": False, "add_qkv_bias": False,
            "make_vocab_size_divisible_by": 1, "ffn_hidden_size": 128,
        },
    })


def _load_model(model_path: Optional[str]):
    """--model: a train_dist-style YAML, or None for the example model."""
    if model_path is None:
        return _example_model().model
    from hetu_galvatron_tpu.core.arguments import args_from_cli
    from hetu_galvatron_tpu.utils.hf_config_adapter import (
        resolve_model_config,
    )

    args = args_from_cli([model_path], mode="train_dist")
    return resolve_model_config(args).model


def run_doctor(plan: str, model_path: Optional[str], world: Optional[int],
               *, schedule_impl: str = "compiled",
               tp_overlap: bool = True) -> int:
    from hetu_galvatron_tpu.analysis.plan_doctor import diagnose_plan

    cfg = _load_model(model_path)
    report = diagnose_plan(plan, cfg, world, schedule_impl=schedule_impl,
                           tp_overlap=tp_overlap)
    report.render()
    return 0 if report.ok else 1


def run_census(verbose: bool = True) -> int:
    """Census smoke on the acceptance plan (compiled 1F1B step, exact
    count cross-check) + the serving prefill/decode programs."""
    _force_cpu_devices()
    from hetu_galvatron_tpu.analysis.census import (
        census_compiled_step,
        census_serving_programs,
        check_census,
    )
    from hetu_galvatron_tpu.core.args_schema import ServingArgs
    from hetu_galvatron_tpu.observability.telemetry import (
        plan_collective_counts,
    )
    from hetu_galvatron_tpu.runtime.hybrid_config import (
        get_hybrid_parallel_config,
    )

    args = _example_model()
    args.parallel.config_mode = "json"
    args.parallel.galvatron_config_path = ACCEPTANCE_PLAN
    hpc = get_hybrid_parallel_config(args, 8)
    problems: List[str] = []

    c = census_compiled_step(args.model, hpc, args.train, tp_overlap=True)
    predicted = plan_collective_counts(hpc, args.model, tp_overlap=True)
    if verbose:
        print(f"census: compiled 1F1B step "
              f"[{hpc.describe()}] -> {c.counts} "
              f"(markers {c.permutes_by_marker})")
        print(f"census: plan arithmetic predicts {predicted}")
    if not c.donated_args:
        problems.append("compiled step: no donated arguments — the fused "
                        "optimizer step must donate (params, opt) or live "
                        "memory doubles")
    problems += check_census(c, predicted, program="compiled_step")
    for n in c.notes:
        print(f"census note: {n}")

    # serving prefill + decode + prefix-prefill + speculative verify:
    # single-device tiny engine; the check is marker coverage + no host
    # callbacks in the token-latency path (prefix_cache/spec_decode on so
    # the new program families are censused too)
    serving = ServingArgs(max_batch_size=2, kv_block_size=8,
                          max_seq_len=32, num_kv_blocks=10,
                          prefix_cache=True, spec_decode=True, spec_k=2)
    for name, sc in census_serving_programs(
            args.model, serving=serving).items():
        if verbose:
            print(f"census: serving {name} -> {sc.counts or '{}'}")
        problems += check_census(sc, program=f"serving {name}")

    for p in problems:
        print(f"CENSUS FAILURE: {p}")
    print(f"census: {'OK' if not problems else 'FAILED'}")
    return 0 if not problems else 1


def run_lint(update_baseline: bool = False, verbose: bool = True) -> int:
    from hetu_galvatron_tpu.analysis.lint import (
        lint_package,
        load_baseline,
        new_findings,
        save_baseline,
        stale_baseline,
    )

    findings = lint_package()
    baseline = load_baseline()
    if update_baseline:
        save_baseline(findings, keep=baseline)
        print(f"lint: baseline rewritten with {len(findings)} finding(s); "
              "fill in any 'TODO: justify or fix' entries")
        return 0
    new = new_findings(findings, baseline)
    stale = stale_baseline(findings, baseline)
    if verbose:
        print(f"lint: {len(findings)} finding(s), "
              f"{len(findings) - len(new)} baselined, {len(new)} new")
    for f in new:
        print(f"LINT: {f}")
    if stale:
        # stale entries FAIL the gate too (same contract as the tier-1
        # test): the baseline must only ever describe live findings
        print(f"lint: {len(stale)} baselined finding(s) no longer occur — "
              "prune them with --update-baseline:")
        for k in stale[:10]:
            print(f"  stale: {k}")
    if new:
        verdict = ("FAILED (new findings — fix them or baseline with a "
                   "justification via --update-baseline)")
    elif stale:
        verdict = "FAILED (stale baseline — prune with --update-baseline)"
    else:
        verdict = "OK"
    print(f"lint: {verdict}")
    return 0 if not new and not stale else 1


def run_all() -> int:
    """The CI gate: plan doctor over every committed example plan, the
    census smoke, the lint baseline gate."""
    _force_cpu_devices()
    rc = 0
    for plan in sorted(glob.glob(os.path.join(EXAMPLE_PLAN_DIR, "*.json"))):
        rc |= run_doctor(plan, None, 8)
        print()
    rc |= run_census()
    print()
    rc |= run_lint()
    print()
    print(f"check --all: {'OK' if rc == 0 else 'FAILED'}")
    return rc


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m hetu_galvatron_tpu.cli.check",
        description="static analysis suite: plan doctor, jaxpr collective "
                    "census, AST lint")
    p.add_argument("--plan", help="plan JSON to diagnose (Pass 1)")
    p.add_argument("--model", help="train_dist-style YAML config for the "
                   "model the plan targets (default: the tiny example "
                   "model the committed plans were written for)")
    p.add_argument("--world", type=int, default=None,
                   help="world size to validate the plan against "
                   "(default: the smallest world the plan fits)")
    p.add_argument("--schedule-impl", choices=("compiled", "host"),
                   default="compiled", help="launcher schedule impl the "
                   "doctor should predict for (default compiled)")
    p.add_argument("--no-tp-overlap", action="store_true",
                   help="doctor: assume tp_overlap.enable is off")
    p.add_argument("--census", action="store_true",
                   help="run the jaxpr collective census (Pass 2)")
    p.add_argument("--lint", action="store_true",
                   help="run the AST lint against the baseline (Pass 3)")
    p.add_argument("--update-baseline", action="store_true",
                   help="rewrite the lint baseline from current findings, "
                   "preserving existing justifications")
    p.add_argument("--all", action="store_true",
                   help="every pass on the committed examples (the CI "
                   "step)")
    a = p.parse_args(argv)

    if a.all:
        return run_all()
    rc = None
    if a.plan:
        _force_cpu_devices()
        rc = run_doctor(a.plan, a.model, a.world,
                        schedule_impl=a.schedule_impl,
                        tp_overlap=not a.no_tp_overlap)
    if a.census:
        rc = (rc or 0) | run_census()
    if a.lint or a.update_baseline:
        rc = (rc or 0) | run_lint(update_baseline=a.update_baseline)
    if rc is None:
        p.print_help()
        return 2
    return rc


if __name__ == "__main__":
    sys.exit(main())
