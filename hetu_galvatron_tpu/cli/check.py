"""Static checks CLI: ``python -m hetu_galvatron_tpu.cli.check``.

Run the five-pass static analysis suite (``analysis/``) on CPU — no TPU,
no training step — BEFORE burning accelerator time:

* ``--plan plan.json [--model cfg.yaml] [--world N]`` — Pass 1, the plan
  doctor: per-layer engine/kernel report with actionable errors for
  malformed plans.
* ``--census`` — Pass 2: trace the compiled 1F1B step for the committed
  acceptance plan plus the serving prefill/decode programs, census their
  collectives, verify named_scope marker coverage and the exact-count
  cross-check against the plan arithmetic
  (``telemetry.plan_collective_counts``).
* ``--lint [--update-baseline | --prune-baseline]`` — Pass 3: the AST
  lint with the committed baseline (``analysis/lint_baseline.json``);
  the gate is zero NEW findings. ``--prune-baseline`` auto-removes STALE
  fingerprints only (no new finding is ever auto-accepted).
* ``--memory [--hbm-gb N]`` — Pass 4, the memory doctor: static
  per-device peak-HBM accounting for the committed example plans
  (model states / activations / compiled-engine stage buffer / vocab
  replication / serving KV pool), cross-checked per component against
  the search engine's memory cost model; ``--hbm-gb`` rejects plans
  whose predicted peak exceeds the budget — the SAME predicate the
  search engine prunes with (``search.hbm_budget_gb``).
* ``--flow`` — Pass 5, the sharding-flow analysis: the census extended
  from counts to BYTES (per-collective megabytes cross-checked exactly
  against ``telemetry.plan_collective_bytes``), plus reshard detection
  (stray all-gathers, double-resharded values) and the donation audit
  over the step + serving programs.
* ``--calibration`` — Pass 6: the calibration self-check — synthetic
  residual store -> α-β re-fit -> plan-regret sentinel round-trip with
  known ground truth.
* ``--schedules`` — Pass 7: the collective-schedule self-check —
  synthesize the ``collectives/`` schedule space over a set of group
  shapes, statically verify every schedule, price the space with
  synthetic link curves (ring-fit inversion exactness, the
  small/large-payload winner flip, missing-curve family drop), and
  probe that a mutated schedule is rejected diagnostically.
* ``--all`` — every pass on the committed examples. This is the CI step
  (``__graft_entry__.dryrun_multichip`` runs it and tier-1 asserts it
  green). The partition-time HLO walk (``sharding_flow.hlo_collectives``)
  compiles programs and rides the slow test tier instead.

Exit code 0 = clean, 1 = findings/errors, 2 = usage.
"""

from __future__ import annotations

import argparse
import glob
import os
import sys
from typing import List, Optional

EXAMPLE_PLAN_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "profiles", "example_plans")
ACCEPTANCE_PLAN = os.path.join(
    EXAMPLE_PLAN_DIR, "galvatron_config_acceptance_tp2dp2pp2.json")


def _force_cpu_devices(n: int = 8) -> None:
    """Static analysis must run on CPU with no accelerator: force the
    virtual host platform BEFORE jax initializes (a no-op when the test
    harness already did). APPEND to any pre-existing XLA_FLAGS — a host
    exporting e.g. --xla_dump_to must not silently lose the device-count
    flag (the tools/pipeline_dispatch_bench.py pattern)."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (flags + " " if flags else "") + \
            f"--xla_force_host_platform_device_count={n}"
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")


def _example_model():
    """The tiny 4-layer model the committed example plans were written
    for (the dryrun/test shape: every kernel family exercisable on the
    8-device virtual mesh)."""
    from hetu_galvatron_tpu.core.args_schema import CoreArgs

    return CoreArgs.model_validate({
        "model": {
            "hidden_size": 64, "num_hidden_layers": 4,
            "num_attention_heads": 4, "vocab_size": 256,
            "seq_length": 16, "max_position_embeddings": 32,
            "hidden_act": "swiglu", "normalization": "rmsnorm",
            "position_embedding_type": "rope", "tie_word_embeddings": False,
            "add_bias_linear": False, "add_qkv_bias": False,
            "make_vocab_size_divisible_by": 1, "ffn_hidden_size": 128,
        },
    })


def _load_model(model_path: Optional[str]):
    """--model: a train_dist-style YAML, or None for the example model."""
    if model_path is None:
        return _example_model().model
    from hetu_galvatron_tpu.core.arguments import args_from_cli
    from hetu_galvatron_tpu.utils.hf_config_adapter import (
        resolve_model_config,
    )

    args = args_from_cli([model_path], mode="train_dist")
    return resolve_model_config(args).model


def run_doctor(plan: str, model_path: Optional[str], world: Optional[int],
               *, schedule_impl: str = "compiled",
               tp_overlap: bool = True) -> int:
    from hetu_galvatron_tpu.analysis.plan_doctor import diagnose_plan

    cfg = _load_model(model_path)
    report = diagnose_plan(plan, cfg, world, schedule_impl=schedule_impl,
                           tp_overlap=tp_overlap)
    report.render()
    return 0 if report.ok else 1


def run_census(verbose: bool = True) -> int:
    """Census smoke on the acceptance plan (compiled 1F1B step, exact
    count cross-check) + the serving prefill/decode programs."""
    _force_cpu_devices()
    from hetu_galvatron_tpu.analysis.census import (
        census_compiled_step,
        census_serving_programs,
        check_census,
    )
    from hetu_galvatron_tpu.observability.telemetry import (
        plan_collective_counts,
    )
    from hetu_galvatron_tpu.runtime.hybrid_config import (
        get_hybrid_parallel_config,
    )

    args = _example_model()
    args.parallel.config_mode = "json"
    args.parallel.galvatron_config_path = ACCEPTANCE_PLAN
    hpc = get_hybrid_parallel_config(args, 8)
    problems: List[str] = []

    c = census_compiled_step(args.model, hpc, args.train, tp_overlap=True)
    predicted = plan_collective_counts(hpc, args.model, tp_overlap=True)
    if verbose:
        print(f"census: compiled 1F1B step "
              f"[{hpc.describe()}] -> {c.counts} "
              f"(markers {c.permutes_by_marker})")
        print(f"census: plan arithmetic predicts {predicted}")
    if not c.donated_args:
        problems.append("compiled step: no donated arguments — the fused "
                        "optimizer step must donate (params, opt) or live "
                        "memory doubles")
    problems += check_census(c, predicted, program="compiled_step")
    for n in c.notes:
        print(f"census note: {n}")

    # serving prefill + decode + prefix-prefill + speculative verify:
    # single-device tiny engine; the check is marker coverage + no host
    # callbacks in the token-latency path (prefix_cache/spec_decode on so
    # the new program families are censused too)
    serving = _census_serving_args()
    for name, sc in census_serving_programs(
            args.model, serving=serving).items():
        if verbose:
            print(f"census: serving {name} -> {sc.counts or '{}'}")
        problems += check_census(sc, program=f"serving {name}")

    for p in problems:
        print(f"CENSUS FAILURE: {p}")
    print(f"census: {'OK' if not problems else 'FAILED'}")
    return 0 if not problems else 1


def _census_serving_args():
    """The serving shape every serving-program pass censuses (prefix
    cache + spec decode on, so all program families are covered)."""
    from hetu_galvatron_tpu.core.args_schema import ServingArgs

    return ServingArgs(max_batch_size=2, kv_block_size=8,
                       max_seq_len=32, num_kv_blocks=10,
                       prefix_cache=True, spec_decode=True, spec_k=2)


def run_memory(hbm_gb: Optional[float] = None, verbose: bool = True,
               schedule_impl: str = "compiled") -> int:
    """Pass 4: the memory doctor over every committed example plan, plus
    a serving-mode row (KV pool + prefix budget) on the acceptance plan.
    ``--hbm-gb`` turns the accounting into a gate; ``--schedule-impl``
    picks the engine convention to account for (compiled — the
    conservative default the search's HBM gate also uses — adds the
    stage-input buffer and the vocab replication premium)."""
    from hetu_galvatron_tpu.analysis.memory_doctor import diagnose_memory

    model = _example_model().model
    rc = 0
    for plan in sorted(glob.glob(os.path.join(EXAMPLE_PLAN_DIR, "*.json"))):
        report = diagnose_memory(plan, model, 8, hbm_gb=hbm_gb,
                                 schedule_impl=schedule_impl)
        if verbose:
            report.render()
            print()
        rc |= 0 if report.ok else 1
    serving = _census_serving_args()
    report = diagnose_memory(ACCEPTANCE_PLAN, model, 8, hbm_gb=hbm_gb,
                             serving=serving,
                             schedule_impl=schedule_impl)
    if verbose:
        print("(serving mode: paged KV pool + prefix-cache budget)")
        report.render()
    rc |= 0 if report.ok else 1
    print(f"memory doctor: {'OK' if rc == 0 else 'FAILED'} (all plans)")
    return rc


def run_flow(verbose: bool = True) -> int:
    """Pass 5: the sharding-flow byte census on the acceptance plan's
    compiled step (exact cross-check against
    ``telemetry.plan_collective_bytes``, donation audit, reshard lint)
    plus the serving program families (reshard lint; their params stay
    undonated by design)."""
    _force_cpu_devices()
    from hetu_galvatron_tpu.analysis.sharding_flow import (
        check_donation,
        check_flow,
        flow_compiled_step,
        flow_serving_programs,
    )
    from hetu_galvatron_tpu.observability.telemetry import (
        plan_collective_bytes,
    )
    from hetu_galvatron_tpu.runtime.hybrid_config import (
        get_hybrid_parallel_config,
    )

    args = _example_model()
    args.parallel.config_mode = "json"
    args.parallel.galvatron_config_path = ACCEPTANCE_PLAN
    hpc = get_hybrid_parallel_config(args, 8)
    problems: List[str] = []

    pf = flow_compiled_step(args.model, hpc, args.train, tp_overlap=True)
    predicted = plan_collective_bytes(hpc, args.model, tp_overlap=True)
    if verbose:
        cats = {k: round(v, 6) for k, v in pf.flow.mb_by_cat.items()}
        marks = {k: round(v, 6)
                 for k, v in pf.flow.permute_mb_by_marker.items()}
        pred = {k: round(v, 6) for k, v in predicted.items()}
        print(f"flow: compiled 1F1B step [{hpc.describe()}] moves "
              f"{cats} MB (markers {marks})")
        print(f"flow: plan arithmetic predicts {pred} MB")
        print(f"flow: donation — {pf.donation.donated_mb:.2f} MB donated, "
              f"{pf.donation.undonated_mb:.2f} MB undonated")
    problems += check_flow(pf.flow, predicted, program="compiled_step")
    problems += check_donation(pf.donation, program="compiled_step")
    problems += pf.reshard_problems
    for n in pf.flow.notes:
        print(f"flow note: {n}")

    for name, spf in flow_serving_programs(
            args.model, serving=_census_serving_args()).items():
        if verbose:
            scats = {k: round(v, 6)
                     for k, v in spf.flow.mb_by_cat.items()} or "{}"
            print(f"flow: serving {name} -> {scats} MB "
                  f"(donated {spf.donation.donated_mb:.2f} MB)")
        problems += spf.reshard_problems

    for p in problems:
        print(f"FLOW FAILURE: {p}")
    print(f"flow: {'OK' if not problems else 'FAILED'}")
    return 0 if not problems else 1


def run_lint(update_baseline: bool = False, prune_stale: bool = False,
             verbose: bool = True) -> int:
    from hetu_galvatron_tpu.analysis.lint import (
        lint_package,
        load_baseline,
        new_findings,
        prune_baseline,
        save_baseline,
        stale_baseline,
    )

    findings = lint_package()
    baseline = load_baseline()
    if prune_stale:
        removed = prune_baseline(findings)
        print(f"lint: pruned {len(removed)} stale baseline entr"
              f"{'y' if len(removed) == 1 else 'ies'}")
        for k in removed[:10]:
            print(f"  pruned: {k}")
        baseline = load_baseline()
        # fall through: the gate still runs, so a prune that leaves NEW
        # findings behind stays red (pruning never accepts new findings)
    if update_baseline:
        save_baseline(findings, keep=baseline)
        print(f"lint: baseline rewritten with {len(findings)} finding(s); "
              "fill in any 'TODO: justify or fix' entries")
        return 0
    new = new_findings(findings, baseline)
    stale = stale_baseline(findings, baseline)
    if verbose:
        print(f"lint: {len(findings)} finding(s), "
              f"{len(findings) - len(new)} baselined, {len(new)} new")
    for f in new:
        print(f"LINT: {f}")
    if stale:
        # stale entries FAIL the gate too (same contract as the tier-1
        # test): the baseline must only ever describe live findings
        print(f"lint: {len(stale)} baselined finding(s) no longer occur — "
              "prune them with --update-baseline:")
        for k in stale[:10]:
            print(f"  stale: {k}")
    if new:
        verdict = ("FAILED (new findings — fix them or baseline with a "
                   "justification via --update-baseline)")
    elif stale:
        verdict = "FAILED (stale baseline — prune with --update-baseline)"
    else:
        verdict = "OK"
    print(f"lint: {verdict}")
    return 0 if not new and not stale else 1


def run_calibration() -> int:
    """Pass 6 — calibration self-check (``observability/calibration.py``):
    a synthetic end-to-end exercise of the store -> re-fit -> regret loop
    with known ground truth. Appends two runs' worth of residual points
    drawn from a known α-β "truth" curve (plus a foreign-fingerprint
    batch that must be excluded), re-fits, and asserts the calibrated
    curve recovers the truth, the profile round-trips through BOTH α-β
    parsers with provenance intact, and the regret sentinel triggers on a
    seeded stale-plan case while staying quiet when calibrated == prior."""
    import tempfile

    from hetu_galvatron_tpu.core.search_engine.profiles import (
        read_alpha_beta,
        read_alpha_beta_algos,
        read_profile_provenance,
    )
    from hetu_galvatron_tpu.observability.calibration import (
        ResidualStore,
        evaluate_plan_regret,
        refit_profile,
        write_calibrated_profile,
    )

    print("== calibration self-check ==")
    failures: List[str] = []

    def check(ok: bool, what: str) -> None:
        print(f"  {'ok' if ok else 'FAIL'}: {what}")
        if not ok:
            failures.append(what)

    with tempfile.TemporaryDirectory() as td:
        store = ResidualStore(os.path.join(td, "residuals.jsonl"))
        fp = {"device": "synthetic", "world": 8, "mesh": [2, 2, 2]}
        alien = {"device": "synthetic", "world": 4, "mesh": [1, 2, 2]}
        a_true, b_true = 0.05, 250.0
        sizes = (1.0, 2.0, 4.0, 8.0, 16.0)

        def batch(scale):
            return [{"collective": "allreduce", "group": "4_1",
                     "alg": "flat", "mb": mb,
                     "ms": (a_true + mb / b_true) * scale, "w": 1.0}
                    for mb in sizes]

        store.append(batch(1.0), fingerprint=fp, run_id="run0")
        store.append(batch(1.02), fingerprint=fp, run_id="run1")
        # a foreign mesh's (wildly different) points must not pollute
        store.append([{"collective": "allreduce", "group": "4_1",
                       "alg": "flat", "mb": mb, "ms": 50.0, "w": 1.0}
                      for mb in sizes], fingerprint=alien, run_id="alien")
        pts = store.load(fingerprint=fp)
        check(len(pts) == 2 * len(sizes), "store round-trip keeps only "
              f"fingerprint-matched points ({len(pts)})")
        prof, meta = refit_profile(pts, min_points=4)
        pair = read_alpha_beta(prof).get("4_1")
        check(pair is not None, "re-fit emitted a flat 4_1 curve")
        if pair:
            a_fit, b_fit = pair
            check(abs(a_fit - a_true) < 0.02 * max(a_true, 1e-9) + 5e-3
                  and abs(b_fit - b_true) / b_true < 0.05,
                  f"fitted curve recovers truth (α {a_fit:.4f}~{a_true}, "
                  f"β {b_fit:.1f}~{b_true})")
        curve_meta = meta.get("curves", {}).get("4_1/flat", {})
        check(curve_meta.get("method") == "regression"
              and curve_meta.get("points", 0) >= 4,
              "provenance records method + point count")
        # file round-trip through both parsers, meta intact
        prof["calibration_meta"] = dict(meta, fingerprint=fp)
        prof["allreduce_size_4_consec_1_alg_ring_lvl_ici_alpha_ms"] = 0.04
        prof["allreduce_size_4_consec_1_alg_ring_lvl_ici_beta_mb_per_ms"] \
            = 260.0
        path = write_calibrated_profile(
            os.path.join(td, "calibrated_profile.json"), prof)
        check("4_1" in read_alpha_beta(path)
              and read_alpha_beta_algos(path)
              .get("4_1", {}).get("ring_ici") is not None,
              "profile file round-trips through both α-β parsers")
        check(read_profile_provenance(path)
              .get("source") == "runtime-calibrated",
              "provenance survives the file round-trip")

        # regret sentinel: calibration halves the comm-heavy runner-up's
        # collective cost, so it overtakes a compute-identical incumbent
        prior_ab = {"2_1": (0.1, 100.0), "4_0": (0.1, 100.0),
                    "4_1": (0.1, 100.0)}
        calib_ab = {"2_1": (0.05, 200.0), "4_0": (0.05, 200.0),
                    "4_1": (0.05, 200.0)}
        incumbent = {"time_cost_ms": 100.0, "pp": 1, "bsz": 8, "chunks": 2,
                     "layers": [{"tp": 1, "dp": 2}] * 2}
        hungry = {"time_cost_ms": 101.0, "pp": 1, "bsz": 8, "chunks": 2,
                  "layers": [{"tp": 4, "dp": 2}] * 2}
        # 64-MB tp activation messages make the runner-up comm-dominated:
        # calibration (halved α, doubled β) shrinks ITS priced comm far
        # more than the incumbent's small dp buffers, flipping the order
        kw = dict(seq_len=4096, hidden_size=4096, param_mb=8.0,
                  mixed_precision=True, threshold=0.001)
        res = evaluate_plan_regret(incumbent, [hungry],
                                   prior=(prior_ab, None),
                                   calibrated=(calib_ab, None), **kw)
        check(bool(res["triggered"]) and res["regret_ms"] > 0,
              f"seeded stale plan triggers regret "
              f"({res['regret_ms']:.3f} ms)")
        quiet = evaluate_plan_regret(incumbent, [hungry],
                                     prior=(prior_ab, None),
                                     calibrated=(prior_ab, None), **kw)
        check(not quiet["triggered"] and quiet["regret_ms"] == 0.0,
              "calibrated == prior stays quiet")

    print(f"calibration: {'OK' if not failures else 'FAILED'}")
    return 0 if not failures else 1


def run_schedules() -> int:
    """Pass 7 — collective-schedule self-check (``collectives/``): for a
    set of (group, cross) shapes covering the dryrun mesh and the odd /
    hierarchical corners, synthesize the full schedule space, run every
    schedule through the static verifier, and price it with synthetic
    link curves — asserting the ring-fit inversion reproduces the fitted
    curve on the ring schedule it was inverted from, every synthesized
    family prices (min-over-curves never silently shrinks), the
    latency/bandwidth regimes really flip the winner (trees at tiny
    payloads, ring/torus at bulk), a missing link curve DROPS a family
    rather than inventing a number, and a mutated schedule is rejected
    with a diagnostic naming the offending step — never a traceback."""
    import dataclasses

    from hetu_galvatron_tpu.collectives.ir import ScheduleError
    from hetu_galvatron_tpu.collectives.pricing import (
        invert_ring_fit,
        price_schedule_ms,
        price_space,
    )
    from hetu_galvatron_tpu.collectives.synthesize import (
        ring_all_reduce,
        synthesize_space,
    )
    from hetu_galvatron_tpu.collectives.verify import verify

    print("== collective-schedule self-check ==")
    failures: List[str] = []

    def check(ok: bool, what: str) -> None:
        print(f"  {'ok' if ok else 'FAIL'}: {what}")
        if not ok:
            failures.append(what)

    shapes = ((2, 1), (4, 1), (6, 1), (8, 1), (8, 2), (12, 3), (16, 4))
    for n, cross in shapes:
        space = synthesize_space(n, cross=cross)
        bad: List[str] = []
        for name, sched in space.items():
            try:
                verify(sched)
            except ScheduleError as e:
                bad.append(f"{name}: {e}")
        check(not bad,
              f"n={n} cross={cross}: all {len(space)} schedules verify"
              + (f" — {bad[0]}" if bad else ""))
        intra = n // cross if cross > 1 else n
        curves = {"ici": invert_ring_fit(0.05, 10.0, max(intra, 2))}
        if cross > 1:
            curves["dcn"] = invert_ring_fit(0.5, 1.0, max(cross, 2))
        prices = price_space(space, 8.0, curves)
        check(set(prices) == set(space)
              and all(v > 0 for v in prices.values()),
              f"n={n} cross={cross}: every family prices > 0 "
              f"({len(prices)}/{len(space)})")

    # ring-fit inversion exactness: pricing the ring schedule with the
    # link curve inverted from its own fit must give back the fit
    a_fit, b_fit = 0.05, 10.0
    ici8 = {"ici": invert_ring_fit(a_fit, b_fit, 8)}
    exact = True
    for mb in (0.001, 1.0, 64.0):
        got = price_schedule_ms(ring_all_reduce(8), mb, ici8)
        want = a_fit + mb / b_fit
        exact = exact and got is not None and abs(got - want) <= 1e-9 * want
    check(exact, "ring-fit inversion is exact on the ring schedule")

    # the plan flip the search keys on: α-dominated tiny payloads go to
    # a tree family, bandwidth-dominated bulk to ring/torus
    space8 = synthesize_space(8)
    tiny = price_space(space8, 0.0005, ici8)
    bulk = price_space(space8, 64.0, ici8)
    check(min(tiny, key=tiny.get) in ("tree_hd", "tree_bcast"),
          f"tiny payload winner is a tree ({min(tiny, key=tiny.get)})")
    check(min(bulk, key=bulk.get) in ("ring", "torus2d"),
          f"bulk payload winner is ring/torus ({min(bulk, key=bulk.get)})")

    # a link class with no curve drops the family, never invents a price:
    # on the 4x2 hierarchical group the flat ring's seam hops tag every
    # step dcn, while the trees also touch ici — a dcn-only curve set
    # must price the ring and drop the trees
    dcn_only = price_space(synthesize_space(8, cross=2), 8.0,
                           {"dcn": invert_ring_fit(0.5, 1.0, 2)})
    check("ring" in dcn_only and "tree_hd" not in dcn_only,
          "missing ici curve drops the trees, keeps the dcn-only ring")

    # the verifier has teeth: duplicate one step's source rank in a
    # verified ring schedule and it must be rejected naming the step
    sched = ring_all_reduce(4)
    step0 = sched.steps[0]
    mutated = dataclasses.replace(
        sched, steps=(dataclasses.replace(
            step0, xfers=step0.xfers + (step0.xfers[0],)),)
        + sched.steps[1:])
    try:
        verify(mutated)
        check(False, "mutated schedule (duplicate source) is rejected")
    except ScheduleError as e:
        check("step 0" in str(e),
              f"duplicate-source rejection names the step ({e})")

    print(f"schedules: {'OK' if not failures else 'FAILED'}")
    return 0 if not failures else 1


def run_all(hbm_gb: Optional[float] = None,
            schedule_impl: str = "compiled") -> int:
    """The CI gate: plan doctor over every committed example plan, the
    census smoke, the memory doctor with its cost-model cross-check, the
    sharding-flow byte census, and the lint baseline gate."""
    _force_cpu_devices()
    rc = 0
    for plan in sorted(glob.glob(os.path.join(EXAMPLE_PLAN_DIR, "*.json"))):
        rc |= run_doctor(plan, None, 8, schedule_impl=schedule_impl)
        print()
    rc |= run_census()
    print()
    rc |= run_memory(hbm_gb=hbm_gb, schedule_impl=schedule_impl)
    print()
    rc |= run_flow()
    print()
    rc |= run_lint()
    print()
    rc |= run_calibration()
    print()
    rc |= run_schedules()
    print()
    print(f"check --all: {'OK' if rc == 0 else 'FAILED'}")
    return rc


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m hetu_galvatron_tpu.cli.check",
        description="static analysis suite: plan doctor, jaxpr collective "
                    "census, AST lint")
    p.add_argument("--plan", help="plan JSON to diagnose (Pass 1)")
    p.add_argument("--model", help="train_dist-style YAML config for the "
                   "model the plan targets (default: the tiny example "
                   "model the committed plans were written for)")
    p.add_argument("--world", type=int, default=None,
                   help="world size to validate the plan against "
                   "(default: the smallest world the plan fits)")
    p.add_argument("--schedule-impl", choices=("compiled", "host"),
                   default="compiled", help="launcher schedule impl the "
                   "doctor should predict for (default compiled)")
    p.add_argument("--no-tp-overlap", action="store_true",
                   help="doctor: assume tp_overlap.enable is off")
    p.add_argument("--census", action="store_true",
                   help="run the jaxpr collective census (Pass 2)")
    p.add_argument("--lint", action="store_true",
                   help="run the AST lint against the baseline (Pass 3)")
    p.add_argument("--update-baseline", action="store_true",
                   help="rewrite the lint baseline from current findings, "
                   "preserving existing justifications")
    p.add_argument("--prune-baseline", action="store_true",
                   help="remove STALE lint-baseline fingerprints only "
                   "(never accepts new findings), then run the gate")
    p.add_argument("--memory", action="store_true",
                   help="run the memory doctor (Pass 4): static "
                   "per-device peak-HBM accounting + cost-model "
                   "cross-check on the committed example plans")
    p.add_argument("--hbm-gb", type=float, default=None,
                   help="per-device HBM budget in GB: the memory doctor "
                   "REJECTS plans whose predicted peak exceeds it (the "
                   "same predicate search.hbm_budget_gb prunes with)")
    p.add_argument("--flow", action="store_true",
                   help="run the sharding-flow analysis (Pass 5): "
                   "byte-level collective census with the exact "
                   "plan_collective_bytes cross-check, reshard "
                   "detection, and the donation audit")
    p.add_argument("--calibration", action="store_true",
                   help="run the calibration self-check (Pass 6): "
                   "synthetic residual store -> α-β re-fit -> plan-regret "
                   "sentinel round-trip with known ground truth")
    p.add_argument("--schedules", action="store_true",
                   help="run the collective-schedule self-check (Pass 7): "
                   "synthesize -> verify -> price over a set of group "
                   "shapes, with the ring-fit inversion exactness, the "
                   "small/large-payload plan flip, and a mutated-schedule "
                   "rejection probe")
    p.add_argument("--all", action="store_true",
                   help="every pass on the committed examples (the CI "
                   "step)")
    a = p.parse_args(argv)

    if a.all:
        return run_all(hbm_gb=a.hbm_gb, schedule_impl=a.schedule_impl)
    rc = None
    if a.plan:
        _force_cpu_devices()
        rc = run_doctor(a.plan, a.model, a.world,
                        schedule_impl=a.schedule_impl,
                        tp_overlap=not a.no_tp_overlap)
    if a.census:
        rc = (rc or 0) | run_census()
    if a.memory:
        rc = (rc or 0) | run_memory(hbm_gb=a.hbm_gb,
                                    schedule_impl=a.schedule_impl)
    if a.flow:
        rc = (rc or 0) | run_flow()
    if a.calibration:
        rc = (rc or 0) | run_calibration()
    if a.schedules:
        rc = (rc or 0) | run_schedules()
    if a.lint or a.update_baseline or a.prune_baseline:
        rc = (rc or 0) | run_lint(update_baseline=a.update_baseline,
                                  prune_stale=a.prune_baseline)
    if rc is None:
        p.print_help()
        return 2
    return rc


if __name__ == "__main__":
    sys.exit(main())
