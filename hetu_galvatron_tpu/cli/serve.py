"""Serving launcher: request stream -> continuous-batching engine -> token
streams.

The offline ``cli/generate.py`` decodes ONE prompt per invocation; this
frontend drives the serving engine (``serving/engine.py``) with many
concurrent requests::

    python -m hetu_galvatron_tpu.cli.serve <model.yaml> \
        requests=<requests.jsonl> [tokenizer=byte|<hf-name-or-path>] \
        [ckpt=<framework ckpt root>] [hf_path=<hf checkpoint dir>] \
        [metrics=<metrics.jsonl>] [stream=1] [watch=<poll seconds>] \
        [serving.* / model.* / parallel.* overrides]

    # one-shot form (single request):
    python -m hetu_galvatron_tpu.cli.serve <model.yaml> prompt="..." \
        max_new_tokens=64

Each line of ``requests.jsonl`` is one request::

    {"prompt": "...", "max_new_tokens": 32, "temperature": 0.8,
     "seed": 7, "arrival_offset_s": 0.5}

``arrival_offset_s`` staggers submission relative to startup (a recorded
trace replays with its original arrival pattern). With ``stream=1`` every
token is printed as a JSONL event as its request's stream drains —
requests print in submission order (the engine generates them
concurrently; per-request TTFT in the metrics reflects actual production
time); ``stream=0`` prints one completion record per request. Serving metrics (TTFT / inter-token
latency percentiles, queue depth, KV occupancy, tokens/sec — see README
"Serving") land in ``metrics`` and render with ``cli/summarize.py``.

Shared-prefix traffic: ``serving.prefix_cache=1`` turns on the radix
prefix cache (requests whose prompts share cached block-aligned prefixes
skip that prefill entirely; ``serve/prefix_hit_rate`` lands in the
metrics). ``serving.spec_decode=1`` adds lossless speculative decoding
(``serving.spec_k`` drafted tokens per step via n-gram prompt-lookup,
verified in one batched pass; greedy output is bit-identical, and
``serve/spec_accept_rate`` reports how often drafts paid off).

A small DRAFT MODEL instead of the n-gram draft:
``serving.spec_draft=model`` with ``draft_model=<model.yaml>`` (the
draft architecture — its vocab must match the target's) and optionally
``draft_ckpt=<framework ckpt root>`` for the draft weights; without a
checkpoint the draft serves random weights (smoke mode, warned). The
engine already took ``draft_params``/``draft_cfg`` — this is the CLI
path to it.

Zero-downtime weight rolls: ``watch=<seconds>`` (with ``ckpt=<root>``)
polls the checkpoint root and hot-swaps every newly COMMITTED step into
the live engine via ``ServingEngine.swap_weights`` — no request is
dropped, the jitted programs never recompile, and the stall lands in the
``serve/swap_stall_ms`` histogram (``serve/weight_swaps`` counts rolls).
A training run writing checkpoints into the same root therefore serves
its own freshest weights continuously.

With more than one visible device the decode runs under the plan's GSPMD
shardings exactly like ``cli/generate.py`` (pure-TP submesh unless explicit
``parallel.*`` degrees are given); the KV pool's head axis follows the
plan's attention tp axes. The offline ``generate`` CLI remains supported
for single prompts.
"""

from __future__ import annotations

import json
import sys
import time


def _read_requests(kv):
    if kv.get("requests"):
        out = []
        with open(kv["requests"]) as f:
            for line in f:
                line = line.strip()
                if line:
                    out.append(json.loads(line))
        return out
    req = {"prompt": kv["prompt"]}
    for key in ("max_new_tokens", "temperature", "seed"):
        if key in kv:
            req[key] = float(kv[key]) if key == "temperature" else int(kv[key])
    return [req]


def _ckpt_params(ckdir: str, params_target):
    """Load a framework checkpoint (a step_* dir or a root holding them)
    into the given eval_shape target; returns (params, resolved dir,
    step). Shared by the target-model and draft-model load paths."""
    import os

    from hetu_galvatron_tpu.runtime.checkpoint import (
        latest_checkpoint,
        load_checkpoint,
    )

    if not os.path.basename(ckdir).startswith("step_"):
        found = latest_checkpoint(ckdir)
        if found is None:
            raise FileNotFoundError(
                f"no step_* checkpoint found under {ckdir}")
        ckdir = found
    params, _, step = load_checkpoint(ckdir, params_target)
    return params, ckdir, step


def _load_draft(kv, serving):
    """The draft-model checkpoint path (serving.spec_draft=model):
    resolve ``draft_model=<yaml>`` to a ModelArgs, load ``draft_ckpt``
    weights when given (random smoke weights otherwise), and return
    (draft_params, draft_cfg) for ``ServingEngine``. Returns (None, None)
    when the n-gram draft (or no spec decode) is configured."""
    if not (serving.spec_decode and serving.spec_draft == "model"):
        return None, None
    import jax

    from hetu_galvatron_tpu.core.arguments import args_from_cli
    from hetu_galvatron_tpu.models.builder import init_causal_lm
    from hetu_galvatron_tpu.utils.hf_config_adapter import (
        resolve_model_config,
    )

    if not kv.get("draft_model"):
        raise ValueError(
            "serving.spec_draft=model needs draft_model=<model.yaml> "
            "(the draft architecture); pass draft_ckpt=<ckpt root> for "
            "its weights")
    dargs = args_from_cli([kv["draft_model"]], mode="train_dist")
    draft_cfg = resolve_model_config(dargs).model
    key = jax.random.key(int(kv.get("seed", 0)) + 1)
    if kv.get("draft_ckpt"):
        target = jax.eval_shape(
            lambda k: init_causal_lm(k, draft_cfg)[0], key)
        draft_params, ckdir, step = _ckpt_params(kv["draft_ckpt"], target)
        print(f"loaded draft {ckdir} (step {step})", file=sys.stderr)
    else:
        print("warning: no draft_ckpt given; drafting with RANDOM "
              "weights (smoke mode — accept rate will be ~0)",
              file=sys.stderr)
        draft_params = init_causal_lm(key, draft_cfg)[0]
    return draft_params, draft_cfg


def main(argv=None) -> int:
    argv = list(argv if argv is not None else sys.argv[1:])
    kv_keys = ("prompt", "requests", "max_new_tokens", "temperature", "seed",
               "tokenizer", "ckpt", "hf_path", "metrics", "stream",
               "draft_model", "draft_ckpt", "watch")
    kv = {}
    passthrough = []
    for a in argv:
        k = a.split("=", 1)[0]
        if "=" in a and k in kv_keys:
            kv[k] = a.split("=", 1)[1]
        else:
            passthrough.append(a)
    if "prompt" not in kv and "requests" not in kv:
        print("usage: serve <model.yaml> requests=<jsonl> | prompt=\"...\" "
              "[key=value ...]", file=sys.stderr)
        return 2

    import jax

    from hetu_galvatron_tpu.core.arguments import args_from_cli
    from hetu_galvatron_tpu.cli.preprocess_data import make_tokenizer
    from hetu_galvatron_tpu.models.builder import init_causal_lm
    from hetu_galvatron_tpu.utils.hf_config_adapter import resolve_model_config

    args = args_from_cli(passthrough, mode="train_dist")
    args = resolve_model_config(args)
    cfg = args.model

    tok = make_tokenizer(kv.get("tokenizer"))
    if tok.vocab_size > cfg.vocab_size:
        raise ValueError(
            f"tokenizer vocab {tok.vocab_size} exceeds model vocab "
            f"{cfg.vocab_size}; pass a matching model config")

    watch_s = float(kv.get("watch", 0) or 0)
    if watch_s > 0 and not kv.get("ckpt"):
        print("watch=<seconds> needs ckpt=<checkpoint root> to poll",
              file=sys.stderr)
        return 2

    init_key = jax.random.key(int(kv.get("seed", 0)))
    box = {}

    def _shapes(k):
        p, box["axes"] = init_causal_lm(k, cfg)
        return p

    params_target = jax.eval_shape(_shapes, init_key)
    axes = box["axes"]
    served_step = -1
    if kv.get("ckpt"):
        params, ckdir, served_step = _ckpt_params(kv["ckpt"], params_target)
        print(f"loaded {ckdir} (step {served_step})", file=sys.stderr)
    elif kv.get("hf_path"):
        from hetu_galvatron_tpu.cli.checkpoint_convert import (
            _load_hf_state_dict,
        )
        from hetu_galvatron_tpu.runtime.checkpoint import hf_to_params

        params = hf_to_params(_load_hf_state_dict(kv["hf_path"]), cfg)
        print(f"loaded HF weights from {kv['hf_path']}", file=sys.stderr)
    else:
        print("warning: no ckpt/hf_path given; serving RANDOM weights "
              "(smoke mode)", file=sys.stderr)
        params = init_causal_lm(init_key, cfg)[0]

    # metrics registry: a dedicated JSONL stream for this serving run
    from hetu_galvatron_tpu.observability.registry import MetricsRegistry
    from hetu_galvatron_tpu.observability.sinks import JsonlSink

    metrics_path = kv.get("metrics") or args.serving.metrics_path or \
        "serve_metrics.jsonl"
    registry = MetricsRegistry([JsonlSink(metrics_path)])

    # plan-aware mesh (same pure-TP submesh heuristic as cli/generate.py)
    mesh = hpc = None
    world = len(jax.devices())
    degree_keys = ("parallel.global_tp_deg", "parallel.pp_deg",
                   "parallel.global_cp_deg", "parallel.vocab_tp")
    user_parallel = any(a.split("=", 1)[0] in degree_keys
                        for a in passthrough)
    tp = 1
    while (tp * 2 <= world and cfg.num_attention_heads % (tp * 2) == 0
           and cfg.kv_heads % (tp * 2) == 0):
        tp *= 2
    if world > 1 and (user_parallel or tp > 1):
        from hetu_galvatron_tpu.runtime.hybrid_config import (
            get_hybrid_parallel_config,
        )
        from hetu_galvatron_tpu.runtime.mesh import build_mesh

        if not user_parallel:
            args.parallel.global_tp_deg = tp
            if cfg.padded_vocab_size % tp == 0:
                args.parallel.vocab_tp = tp
            args.parallel.global_train_batch_size = tp
            sub_world = tp
        else:
            sub_world = world
        print(f"serving on {sub_world} devices "
              f"(tp={args.parallel.global_tp_deg})", file=sys.stderr)
        hpc = get_hybrid_parallel_config(args, sub_world)
        mesh = build_mesh(sub_world, 1, devices=jax.devices()[:sub_world])

    from hetu_galvatron_tpu.serving.engine import ServingEngine

    serving = args.serving
    if serving.eos_id is None:
        serving = serving.model_copy(
            update={"eos_id": getattr(tok, "eod_id", None)})
    stream = kv.get("stream", "1") not in ("0", "false", "False")
    draft_params, draft_cfg = _load_draft(kv, serving)
    engine = ServingEngine(params, cfg, serving, mesh=mesh, hpc=hpc,
                           axes_tree=axes if mesh is not None else None,
                           registry=registry,
                           draft_params=draft_params, draft_cfg=draft_cfg)
    if engine.metrics_port is not None:
        # serving.metrics_port: Prometheus text endpoint over the serve/*
        # registry (observability/prometheus.py); port 0 binds ephemeral,
        # serving.metrics_host widens the (loopback-default) bind
        print(f"metrics: http://{serving.metrics_host}:"
              f"{engine.metrics_port}/metrics", file=sys.stderr)

    if serving.prefix_cache or serving.spec_decode:
        print(f"serving features: prefix_cache={serving.prefix_cache} "
              f"spec_decode={serving.spec_decode}"
              + (f" (k={serving.spec_k}, draft={serving.spec_draft})"
                 if serving.spec_decode else ""), file=sys.stderr)
    if serving.trace_requests:
        # request-lifecycle tracing (observability/events.py): timelines
        # + TTFT breakdown render with `summarize <metrics> --timeline`
        print("request tracing: ON (per-request lifecycle events in the "
              "metrics stream)", file=sys.stderr)
    slo_parts = []
    if serving.slo_ttft_ms > 0:
        slo_parts.append(f"ttft<={serving.slo_ttft_ms}ms")
    if serving.slo_itl_ms > 0:
        slo_parts.append(f"itl<={serving.slo_itl_ms}ms")
    if slo_parts:
        # 0 means that SLO is off — never print an impossible 0ms target
        print(f"SLO targets: {' '.join(slo_parts)} (attainment gauges "
              "in serve/slo_*)", file=sys.stderr)
    if serving.flight_dir:
        print(f"flight recorder: dumps to {serving.flight_dir} on engine "
              "fault", file=sys.stderr)
    reqs = _read_requests(kv)
    # compile decode + every prefill bucket BEFORE traffic: TTFT must
    # measure serving latency, not jit compilation
    print("warmup: compiling decode + prefill buckets ...", file=sys.stderr)
    engine.warmup()
    engine.start()

    # watch mode: poll the checkpoint root and hot-swap every newly
    # committed step into the live engine (zero dropped requests, zero
    # recompiles; the stall rides serve/swap_stall_ms)
    watcher = None
    watch_stop = None
    if watch_s > 0:
        import os
        import threading

        from hetu_galvatron_tpu.runtime.checkpoint import latest_checkpoint

        watch_stop = threading.Event()

        def _watch(cur_step=served_step):
            # a step that keeps failing (wrong architecture, torn shards,
            # flaky mount) must not re-download the whole tree every poll
            # forever — but a TRANSIENT fault must not strand the watcher
            # on stale weights either: after 3 consecutive failures the
            # step backs off to one retry per ~30 polls (a newer commit
            # always tries immediately; success clears the slate)
            fails: dict = {}
            skip = 0
            bad_step = None
            while not watch_stop.wait(watch_s):
                step_n = None
                try:
                    found = latest_checkpoint(kv["ckpt"])
                    if not found:
                        continue
                    # advance by the DIRECTORY step (what latest_checkpoint
                    # orders by), never the loaded meta step — a dir whose
                    # name and meta disagree must not re-swap every poll
                    step_n = int(os.path.basename(found)[len("step_"):])
                    if step_n <= cur_step:
                        continue
                    if step_n == bad_step and skip > 0:
                        skip -= 1
                        continue
                    new_params, ckd, _ = _ckpt_params(found, params_target)
                    stall = engine.swap_weights(new_params)
                    print(f"weight swap: step {cur_step} -> {step_n} "
                          f"({ckd}, stall {stall:.1f} ms)",
                          file=sys.stderr)
                    cur_step = step_n
                    fails.pop(step_n, None)
                    bad_step = None
                except Exception as e:  # noqa: BLE001 — keep serving
                    print(f"warning: weight-swap watch failed: {e}",
                          file=sys.stderr)
                    if step_n is not None:
                        fails[step_n] = fails.get(step_n, 0) + 1
                        if fails[step_n] >= 3:
                            bad_step = step_n
                            skip = 30
                            print(f"warning: step {step_n} failed "
                                  f"{fails[step_n]} swap attempts; "
                                  "backing off (retry roughly every "
                                  "30 polls; a newer checkpoint swaps "
                                  "immediately)", file=sys.stderr)

        watcher = threading.Thread(target=_watch, daemon=True,
                                   name="ckpt-watch")
        watcher.start()
        print(f"watching {kv['ckpt']} every {watch_s:g}s for new "
              "committed checkpoints (hot swap)", file=sys.stderr)
    t0 = time.monotonic()
    handles = []
    try:
        for i, r in enumerate(reqs):
            at = float(r.get("arrival_offset_s", 0.0))
            wait = t0 + at - time.monotonic()
            if wait > 0:
                time.sleep(wait)
            ids = tok.encode(r["prompt"])
            if not ids:
                print(json.dumps({"rid": i, "event": "rejected",
                                  "reason": "empty prompt"}))
                continue
            h = engine.submit(
                ids,
                max_new_tokens=r.get("max_new_tokens"),
                temperature=r.get("temperature"),
                seed=int(r.get("seed", 0)))
            handles.append((i, r, h))
            if h.status == "rejected":
                print(json.dumps({"rid": i, "event": "rejected",
                                  "reason": "capacity"}))

        for i, r, h in handles:
            if h.status == "rejected":
                continue
            if stream:
                for t in h.tokens():
                    print(json.dumps({"rid": i, "event": "token",
                                      "text": tok.decode([t])}), flush=True)
            out = h.result()
            eod = getattr(tok, "eod_id", None)
            if eod is not None and eod in out:
                out = out[: out.index(eod)]
            print(json.dumps({
                "rid": i, "event": "done", "status": h.status,
                "reason": h.finish_reason, "n_tokens": len(h.output),
                "ttft_ms": (None if h.ttft_s() is None
                            else round(h.ttft_s() * 1000.0, 3)),
                "text": tok.decode(out)}), flush=True)
    finally:
        if watch_stop is not None:
            watch_stop.set()
            watcher.join(timeout=5.0)
        engine.close()
        registry.close()
    print(f"metrics written to {metrics_path} "
          f"(render: python -m hetu_galvatron_tpu.cli.summarize "
          f"{metrics_path})", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
