"""Strategy-search launcher: ``python -m hetu_galvatron_tpu.cli.search_dist
<config.yaml> [key=value ...]`` (reference models/gpt/search_dist.py:11-33).

Also home of the ELASTIC re-plan internals: when a resume finds the live
world differs from the checkpoint's recorded one, ``replan_for_world``
re-runs this offline-fast search for the new topology (or, with no search
profiles configured, degree-adapts the stored plan), gates the winner
through the memory doctor's HBM budget predicate, and points
``args.parallel`` at the result — the "re-search" leg of
detect -> re-search -> budget-gate -> reshard -> replay."""

from __future__ import annotations

import glob
import os
import sys
from typing import Any, Callable, Dict, Optional


def feed_calibrated_profile(args, world: int,
                            *, log: Callable[[str], None] = print) -> bool:
    """Close the calibration loop: when the runtime calibration pass has
    written a posterior profile
    (``observability.calibration_dir``/calibrated_profile.json, PR 16)
    whose hardware fingerprint matches this search's device kind and
    world size, point ``search.allreduce_bandwidth_config_path`` at it —
    production-trace-fitted curves then price the next plan instead of
    the one-shot profiled priors. The swap is logged with full
    provenance; ``search.use_calibrated=0`` opts out. The mesh leg of
    the fingerprint is plan-shaped and a search has no plan yet, so only
    device + world gate here (the per-curve keys are mesh-agnostic).
    Returns True when the calibrated profile was installed."""
    sa = args.search
    obs = getattr(args, "observability", None)
    cal_dir = getattr(obs, "calibration_dir", None) if obs else None
    if not getattr(sa, "use_calibrated", 1) or not cal_dir:
        return False
    from hetu_galvatron_tpu.core.search_engine.profiles import (
        read_profile_provenance,
    )
    from hetu_galvatron_tpu.observability.calibration import (
        PROFILE_NAME,
        fingerprint_key,
        hardware_fingerprint,
    )

    path = os.path.join(cal_dir, PROFILE_NAME)
    if not os.path.exists(path):
        return False
    meta = read_profile_provenance(path)
    fp = meta.get("fingerprint") or {}
    want = hardware_fingerprint(None, world=world)
    if (str(fp.get("device")) != want["device"]
            or int(fp.get("world", 0) or 0) != int(world)):
        log(f"calibration: ignoring {path} — its fingerprint "
            f"{fingerprint_key(fp)} does not match this search "
            f"({fingerprint_key(want)})")
        return False
    prev = sa.allreduce_bandwidth_config_path
    sa.allreduce_bandwidth_config_path = path
    counts = meta.get("curves") or meta.get("points_per_curve") or {}
    log("calibration: pricing with the runtime-calibrated profile "
        f"{path} (source {meta.get('source', '?')}, fingerprint "
        f"{fingerprint_key(fp)}, {len(counts) or '?'} re-fit curve(s))"
        + (f"; replaces {prev}" if prev else ""))
    return True


def search_plan_for_world(args, world: int, out_dir: str,
                          *, log: Callable[[str], None] = print
                          ) -> Optional[str]:
    """Run the offline strategy search for ``world`` devices using the
    search profiles configured on ``args`` (a resolved CoreArgs); returns
    the written plan path, or None when no feasible plan exists. The
    global batch size is ALWAYS settled to
    ``args.parallel.global_train_batch_size`` (which ``replan_for_world``
    pins to the checkpoint's stored value): an elastic resume must keep
    the data schedule, so the batch geometry is never up for re-search —
    a conflicting ``search.settle_bsz`` is ignored with a warning."""
    from hetu_galvatron_tpu.core.search_engine.engine import SearchEngine
    from hetu_galvatron_tpu.utils.hf_config_adapter import (
        model_layer_configs,
        model_name,
    )

    sa = args.search
    os.makedirs(out_dir, exist_ok=True)
    feed_calibrated_profile(args, world, log=log)
    settled = args.parallel.global_train_batch_size
    if sa.settle_bsz > 0 and sa.settle_bsz != settled:
        log(f"elastic re-search: ignoring search.settle_bsz="
            f"{sa.settle_bsz} — the checkpoint's batch geometry "
            f"(global_bsz {settled}) must survive the topology change "
            "for the exact data-position replay")
    s2 = sa.model_copy(update={
        "num_nodes": 1, "num_devices_per_node": int(world),
        "settle_bsz": int(settled), "output_config_path": out_dir})
    engine = SearchEngine(
        s2, mixed_precision=s2.mixed_precision,
        default_dp_type=s2.default_dp_type, pipeline_type=s2.pipeline_type,
        model_cfg=args.model)
    engine.set_model_info(model_layer_configs(args.model),
                          model_name(args.model),
                          model_type=args.model.model_type)
    engine.initialize()
    throughput = engine.optimize()
    if throughput <= 0:
        return None
    plans = sorted(glob.glob(os.path.join(out_dir, "galvatron_config_*.json")),
                   key=os.path.getmtime)
    log(f"elastic re-search: plan for {world} devices -> {plans[-1]} "
        f"(predicted throughput {throughput:.6f} samples/s)")
    return plans[-1]


def _adapt_degrees(args, world: int, stored_plan: Dict[str, Any],
                   *, log: Callable[[str], None] = print) -> Optional[str]:
    """No search profiles at hand: deterministically adapt the stored
    plan's degrees to the new world (keep tp/cp, shrink dp, then pp, then
    tp) and write them into ``args.parallel`` as a GLOBAL-mode plan.
    Returns None on success, else the reason no adaptation fits."""
    from hetu_galvatron_tpu.utils.strategy import config2strategy

    stored_world = int(stored_plan.get("world_size") or 0)
    try:
        layers, vocab, extras = config2strategy(
            stored_plan, world_size=stored_world or None)
    except Exception as e:  # noqa: BLE001 — plan fingerprint may be legacy
        return f"stored plan fingerprint is unreadable ({e})"
    base = layers[0]
    cp = max(base.cp_size, 1)
    n_layers = len(layers)
    for tp in _halvings(max(base.tp_size, 1)):
        for pp in _halvings(min(max(base.pp_deg, 1), n_layers)):
            grain = pp * tp * cp
            if grain <= world and world % grain == 0:
                par = args.parallel
                par.config_mode = "global"
                par.galvatron_config_path = None
                par.pp_deg = pp
                par.global_tp_deg = tp
                par.global_cp_deg = cp
                par.use_ulysses = bool(base.sp)
                par.global_tp_consec = int(base.tp_consecutive)
                par.global_checkpoint = int(base.checkpoint)
                par.default_dp_type = base.dp_type.short
                stage_world = world // pp
                vtp = max(vocab.vtp, 1)
                while vtp > 1 and stage_world % vtp:
                    vtp //= 2
                par.vocab_tp = vtp
                par.vocab_sp = int(vocab.vsp)
                par.embed_sdp = int(vocab.embed_sdp)
                if extras.get("pipeline_type"):
                    par.pipeline_type = extras["pipeline_type"]
                log("elastic re-plan (no search profiles configured): "
                    f"degree-adapted the stored plan to pp{pp} tp{tp} "
                    f"cp{cp} dp{stage_world // (tp * cp)} vtp{vtp} for "
                    f"{world} devices")
                return None
    return (f"no pp x tp x cp adaptation of the stored plan (pp"
            f"{base.pp_deg} tp{base.tp_size} cp{cp}) divides the live "
            f"world of {world} devices")


def _halvings(n: int):
    while n >= 1:
        yield n
        if n == 1:
            break
        n //= 2


def replan_for_world(args, world: int, stored_plan: Dict[str, Any],
                     *, log: Callable[[str], None] = print
                     ) -> Optional[str]:
    """Point ``args.parallel`` at a plan for ``world`` devices: re-run the
    offline search when profiles are configured, else degree-adapt the
    stored plan — then gate the winner through the memory doctor's HBM
    budget predicate (``analysis/memory_doctor.py::hbm_budget_reason``,
    the exact predicate ``check --memory --hbm-gb`` and the search's own
    pruning hook evaluate). Returns None on success; a TERMINAL reason
    string otherwise (an infeasible or OOM-rejected target plan reproduces
    on every restart — callers exit 17, they do not retry)."""
    sa = args.search
    # the data schedule must survive the topology change: pin the batch
    # geometry to what the checkpoint was trained with before any
    # re-planning — THE one place this invariant lives (the searched
    # plan's own global_bsz/chunks then come from the settled search; the
    # degree-adapt path reads these args directly)
    if stored_plan.get("global_bsz"):
        args.parallel.global_train_batch_size = int(
            stored_plan["global_bsz"])
    if stored_plan.get("chunks"):
        args.parallel.chunks = int(stored_plan["chunks"])
    if sa.time_profiling_path and sa.memory_profiling_path:
        out_dir = os.path.join(
            os.path.abspath(args.ckpt.load or sa.output_config_path
                            or "configs"),
            f"elastic_plan_{world}dev")
        try:
            plan = search_plan_for_world(args, world, out_dir, log=log)
        except Exception as e:  # noqa: BLE001 — search failure is terminal
            return f"elastic re-search failed for {world} devices: {e}"
        if plan is None:
            return (f"elastic re-search found no feasible plan for "
                    f"{world} devices")
        args.parallel.config_mode = "json"
        args.parallel.galvatron_config_path = plan
    else:
        reason = _adapt_degrees(args, world, stored_plan, log=log)
        if reason is not None:
            return reason

    # validate + HBM-gate the winner BEFORE committing to resharding
    from hetu_galvatron_tpu.runtime.hybrid_config import (
        get_hybrid_parallel_config,
    )

    try:
        hpc = get_hybrid_parallel_config(args, world)
    except Exception as e:  # noqa: BLE001 — structural rejection is terminal
        return (f"re-planned configuration is invalid for {world} "
                f"devices: {e}")
    if sa.hbm_budget_gb > 0:
        from hetu_galvatron_tpu.analysis.memory_doctor import (
            hbm_budget_reason,
            peak_mb,
            plan_stage_memory,
        )

        stages = plan_stage_memory(
            hpc.layers, hpc.vocab, args.model,
            global_bsz=hpc.global_bsz, chunks=hpc.chunks,
            pp_division=hpc.pp_division, pipeline_type=hpc.pipeline_type,
            schedule_impl="compiled",
            mixed_precision=args.parallel.mixed_precision != "fp32")
        reason = hbm_budget_reason(peak_mb(stages), sa.hbm_budget_gb)
        if reason is not None:
            return ("elastic target plan rejected by the HBM budget "
                    f"gate: {reason}")
    return None


def main(argv=None) -> int:
    from hetu_galvatron_tpu.core.arguments import args_from_cli
    from hetu_galvatron_tpu.core.search_engine.engine import SearchEngine
    from hetu_galvatron_tpu.utils.hf_config_adapter import (
        model_layer_configs,
        model_name,
        resolve_model_config,
    )

    args = args_from_cli(argv if argv is not None else sys.argv[1:],
                         mode="search")
    args = resolve_model_config(args)
    feed_calibrated_profile(
        args, args.search.num_nodes * args.search.num_devices_per_node)
    engine = SearchEngine(
        args.search,
        mixed_precision=args.search.mixed_precision,
        default_dp_type=args.search.default_dp_type,
        pipeline_type=args.search.pipeline_type,
        # the static HBM gate (search.hbm_budget_gb) accounts the actual
        # model shapes, so the searcher gets the resolved config
        model_cfg=args.model,
    )
    engine.set_model_info(model_layer_configs(args.model),
                          model_name(args.model),
                          model_type=args.model.model_type)
    engine.initialize()
    throughput = engine.optimize()
    # fixed 8-decimal rounding: the golden regression pins the printed
    # string, and raw float repr drifts with formatting-irrelevant digits
    print(f"search done: max throughput {throughput:.8f} samples/s")
    return 0 if throughput > 0 else 1


if __name__ == "__main__":
    sys.exit(main())
