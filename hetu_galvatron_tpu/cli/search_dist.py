"""Strategy-search launcher: ``python -m hetu_galvatron_tpu.cli.search_dist
<config.yaml> [key=value ...]`` (reference models/gpt/search_dist.py:11-33)."""

from __future__ import annotations

import sys


def main(argv=None) -> int:
    from hetu_galvatron_tpu.core.arguments import args_from_cli
    from hetu_galvatron_tpu.core.search_engine.engine import SearchEngine
    from hetu_galvatron_tpu.utils.hf_config_adapter import (
        model_layer_configs,
        model_name,
        resolve_model_config,
    )

    args = args_from_cli(argv if argv is not None else sys.argv[1:],
                         mode="search")
    args = resolve_model_config(args)
    engine = SearchEngine(
        args.search,
        mixed_precision=args.search.mixed_precision,
        default_dp_type=args.search.default_dp_type,
        pipeline_type=args.search.pipeline_type,
        # the static HBM gate (search.hbm_budget_gb) accounts the actual
        # model shapes, so the searcher gets the resolved config
        model_cfg=args.model,
    )
    engine.set_model_info(model_layer_configs(args.model),
                          model_name(args.model),
                          model_type=args.model.model_type)
    engine.initialize()
    throughput = engine.optimize()
    # fixed 8-decimal rounding: the golden regression pins the printed
    # string, and raw float repr drifts with formatting-irrelevant digits
    print(f"search done: max throughput {throughput:.8f} samples/s")
    return 0 if throughput > 0 else 1


if __name__ == "__main__":
    sys.exit(main())
