"""Text-generation launcher: prompt -> tokens -> (sharded) KV-cache decode
-> text.

The reference ships no inference entry point (its attention layer has only
inference-context stubs); this CLI completes the L7 surface over the
generation runtime (models/generate.py + parallel/spmd.py
make_spmd_generate)::

    python -m hetu_galvatron_tpu.cli.generate <model.yaml> \
        prompt="once upon a time" [max_new_tokens=64] [temperature=0.8] \
        [top_k=40] [tokenizer=byte|<hf-name-or-path>] \
        [ckpt=<framework ckpt root>] [hf_path=<hf checkpoint dir>] \
        [model.* / parallel.* overrides]

Weights come from a framework checkpoint (``ckpt=``), an HF checkpoint dir
(``hf_path=``), or random init (smoke/demo). With more than one visible
device the decode runs under the plan's GSPMD shardings (tp/dp) via
``make_spmd_generate``; single-device runs jit the plain generate().
"""

from __future__ import annotations

import os
import sys


def main(argv=None) -> int:
    argv = list(argv if argv is not None else sys.argv[1:])
    kv_keys = ("prompt", "max_new_tokens", "temperature", "top_k",
               "tokenizer", "ckpt", "hf_path", "seed")
    kv = {}
    passthrough = []
    for a in argv:
        k = a.split("=", 1)[0]
        if "=" in a and k in kv_keys:
            kv[k] = a.split("=", 1)[1]
        else:
            passthrough.append(a)
    if "prompt" not in kv:
        print("usage: generate <model.yaml> prompt=\"...\" [key=value ...]",
              file=sys.stderr)
        return 2

    import jax
    import jax.numpy as jnp
    import numpy as np

    from hetu_galvatron_tpu.core.arguments import args_from_cli
    from hetu_galvatron_tpu.cli.preprocess_data import make_tokenizer
    from hetu_galvatron_tpu.models.builder import init_causal_lm
    from hetu_galvatron_tpu.models.generate import generate
    from hetu_galvatron_tpu.utils.hf_config_adapter import resolve_model_config

    args = args_from_cli(passthrough, mode="train_dist")
    args = resolve_model_config(args)
    cfg = args.model

    tok = make_tokenizer(kv.get("tokenizer"))
    if tok.vocab_size > cfg.vocab_size:
        # padded rows hold untrained weights — matching against them would
        # silently embed real tokens into garbage
        raise ValueError(
            f"tokenizer vocab {tok.vocab_size} exceeds model vocab "
            f"{cfg.vocab_size}; pass a matching model config")
    ids = tok.encode(kv["prompt"])
    if not ids:
        raise ValueError("empty prompt after tokenization")
    prompt = jnp.asarray(np.asarray(ids, np.int32)[None, :])

    init_key, sample_key = jax.random.split(
        jax.random.key(int(kv.get("seed", 0))))

    # weights about to be replaced need only an ABSTRACT restore target;
    # the logical-axes tree is plain python data, captured while shaping
    # (eval_shape cannot return string leaves)
    box = {}

    def _shapes(k):
        p, box["axes"] = init_causal_lm(k, cfg)
        return p

    params_target = jax.eval_shape(_shapes, init_key)
    axes = box["axes"]
    if kv.get("ckpt"):
        from hetu_galvatron_tpu.runtime.checkpoint import (
            latest_checkpoint,
            load_checkpoint,
        )

        ckdir = kv["ckpt"]
        if not os.path.basename(ckdir).startswith("step_"):
            found = latest_checkpoint(ckdir)
            if found is None:
                raise FileNotFoundError(
                    f"no step_* checkpoint found under {ckdir}")
            ckdir = found
        params, _, step = load_checkpoint(ckdir, params_target)
        print(f"loaded {ckdir} (step {step})", file=sys.stderr)
    elif kv.get("hf_path"):
        from hetu_galvatron_tpu.cli.checkpoint_convert import (
            _load_hf_state_dict,
        )
        from hetu_galvatron_tpu.runtime.checkpoint import hf_to_params

        params = hf_to_params(_load_hf_state_dict(kv["hf_path"]), cfg)
        print(f"loaded HF weights from {kv['hf_path']}", file=sys.stderr)
    else:
        print("warning: no ckpt/hf_path given; generating from RANDOM "
              "weights (smoke mode)", file=sys.stderr)
        params = init_causal_lm(init_key, cfg)[0]

    n_new = int(kv.get("max_new_tokens", 64))
    gen_kwargs = dict(
        temperature=float(kv.get("temperature", 0.0)),
        top_k=int(kv["top_k"]) if kv.get("top_k") else None,
        eos_id=getattr(tok, "eod_id", None),
    )
    key = sample_key

    # Single-prompt decode cannot shard the batch axis, so multi-device runs
    # use a pure-TP submesh: the largest power-of-2 tp <= world that divides
    # the (kv) head counts. Explicit DEGREE overrides win (other parallel.*
    # keys like mixed_precision must not force a dp-sharded plan onto a
    # batch of one).
    if cfg.model_type == "t5":
        # seq2seq: the prompt is the ENCODER source; decode starts from the
        # start token (HF T5 uses pad id 0). The CLI decodes single-device
        # (one prompt); make_spmd_generate also handles t5 for sharded
        # programmatic decoding.
        from hetu_galvatron_tpu.models.generate import generate_encdec

        out = jax.jit(lambda p, t, k: generate_encdec(
            p, t, cfg, n_new, key=k, **gen_kwargs))(params, prompt, key)
        new_ids = np.asarray(out)[0, 1:].tolist()  # strip the start token
        eod = getattr(tok, "eod_id", None)
        if eod is not None and eod in new_ids:
            new_ids = new_ids[:new_ids.index(eod)]
        print(tok.decode(new_ids))
        return 0

    world = len(jax.devices())
    degree_keys = ("parallel.global_tp_deg", "parallel.pp_deg",
                   "parallel.global_cp_deg", "parallel.global_ep_deg",
                   "parallel.vocab_tp", "parallel.vocab_sp",
                   "parallel.use_ulysses", "parallel.sdp")
    user_parallel = any(a.split("=", 1)[0] in degree_keys
                        for a in passthrough)
    tp = 1
    while (tp * 2 <= world and cfg.num_attention_heads % (tp * 2) == 0
           and cfg.kv_heads % (tp * 2) == 0):
        tp *= 2
    if world > 1 and (user_parallel or tp > 1):
        from hetu_galvatron_tpu.parallel.spmd import (
            make_spmd_generate,
            shard_params,
        )
        from hetu_galvatron_tpu.runtime.hybrid_config import (
            get_hybrid_parallel_config,
        )
        from hetu_galvatron_tpu.runtime.mesh import build_mesh

        if not user_parallel:
            args.parallel.global_tp_deg = tp
            if cfg.padded_vocab_size % tp == 0:
                args.parallel.vocab_tp = tp
            # gbsz only feeds plan validation (must divide by the vocab
            # layer's dp); the actual decode batch is the prompt's
            args.parallel.global_train_batch_size = tp
            sub_world = tp
        else:
            sub_world = world
        print(f"decoding on {sub_world} devices "
              f"(tp={args.parallel.global_tp_deg})", file=sys.stderr)
        hpc = get_hybrid_parallel_config(args, sub_world)
        dp = hpc.layers[0].dp_size
        if prompt.shape[0] % dp:
            raise ValueError(
                f"the plan data-parallelizes the batch {dp} ways but there "
                f"is {prompt.shape[0]} prompt; use tp-only degrees "
                f"(e.g. parallel.global_tp_deg={sub_world}) for "
                "single-prompt decoding")
        mesh = build_mesh(sub_world, 1, devices=jax.devices()[:sub_world])
        fn, pspecs, batch_shd = make_spmd_generate(
            cfg, hpc, mesh, axes, n_new, **gen_kwargs)
        sp = shard_params(params, pspecs, mesh)
        out = fn(sp, jax.device_put(prompt, batch_shd), key)
    else:
        out = jax.jit(lambda p, t, k: generate(
            p, t, cfg, n_new, key=k, **gen_kwargs))(params, prompt, key)

    new_ids = np.asarray(out)[0, prompt.shape[1]:].tolist()
    eod = getattr(tok, "eod_id", None)
    if eod is not None and eod in new_ids:
        new_ids = new_ids[:new_ids.index(eod)]
    print(kv["prompt"] + tok.decode(new_ids))
    return 0


if __name__ == "__main__":
    sys.exit(main())
