"""Model/hardware profiling launcher (reference models/gpt/profiler.py:7-23 +
profile_hardware.py): ``python -m hetu_galvatron_tpu.cli.profiler
<config.yaml> mode=model_profiler|profile_hardware [key=value ...]``."""

from __future__ import annotations

import sys


def main(argv=None) -> int:
    from hetu_galvatron_tpu.core.arguments import args_from_cli
    from hetu_galvatron_tpu.utils.hf_config_adapter import resolve_model_config

    argv = list(argv if argv is not None else sys.argv[1:])
    mode = "model_profiler"
    for a in argv:
        if a.startswith("mode="):
            mode = a.split("=", 1)[1]
    args = args_from_cli(argv, mode=mode)
    args = resolve_model_config(args)

    if args.mode == "profile_hardware":
        from hetu_galvatron_tpu.core.profiler.hardware_profiler import (
            HardwareProfiler,
        )

        paths = HardwareProfiler(args.hardware_profiler).run_all()
    else:
        from hetu_galvatron_tpu.core.profiler.model_profiler import ModelProfiler

        paths = ModelProfiler(args).run()
    for name, path in paths.items():
        print(f"wrote {name}: {path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
