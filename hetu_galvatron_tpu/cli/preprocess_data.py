"""Corpus preparation: text -> tokenized .bin/.idx indexed dataset.

Counterpart of the reference's Megatron preprocessing flow (the reference
consumes externally-preprocessed mmap corpora; this CLI closes the loop):
``python -m hetu_galvatron_tpu.cli.preprocess_data input.txt[,more.txt]
output_prefix [tokenizer=<hf-name-or-path>] [append_eod=1]``.

One document per input line (JSONL with a "text" field also accepted). With
no tokenizer given, a byte-level fallback (vocab 256 + eod 256) keeps the
pipeline dependency-free.
"""

from __future__ import annotations

import json
import sys
from typing import Iterator, List, Optional


def _iter_documents(paths: List[str]) -> Iterator[str]:
    for path in paths:
        with open(path) as f:
            for line in f:
                line = line.rstrip("\n")
                if not line:
                    continue
                if line.lstrip().startswith("{"):
                    try:
                        obj = json.loads(line)
                        yield str(obj.get("text", line))
                        continue
                    except json.JSONDecodeError:
                        pass
                yield line


class ByteTokenizer:
    """Dependency-free fallback: UTF-8 bytes as ids, eod = 256."""

    vocab_size = 257
    eod_id = 256
    mask_id = None

    def encode(self, text: str) -> List[int]:
        return list(text.encode("utf-8"))

    def decode(self, ids: List[int]) -> str:
        return bytes(i for i in ids if i < 256).decode("utf-8",
                                                       errors="replace")


def make_tokenizer(name: Optional[str]):
    if not name or name == "byte":
        return ByteTokenizer()
    from transformers import AutoTokenizer

    tok = AutoTokenizer.from_pretrained(name)

    class _Wrap:
        vocab_size = tok.vocab_size
        eod_id = tok.eos_token_id if tok.eos_token_id is not None else 0
        mask_id = getattr(tok, "mask_token_id", None)

        def encode(self, text: str) -> List[int]:
            return tok.encode(text, add_special_tokens=False)

        def decode(self, ids: List[int]) -> str:
            return tok.decode(ids)

    return _Wrap()


def main(argv=None) -> int:
    from hetu_galvatron_tpu.data.indexed_dataset import write_indexed_dataset

    argv = list(argv if argv is not None else sys.argv[1:])
    pos = [a for a in argv if "=" not in a]
    kv = dict(a.split("=", 1) for a in argv if "=" in a)
    if len(pos) < 2:
        print("usage: preprocess_data <input[,input2...]> <output_prefix> "
              "[tokenizer=<hf-name|byte>] [append_eod=1]", file=sys.stderr)
        return 2
    inputs = pos[0].split(",")
    prefix = pos[1]
    tok = make_tokenizer(kv.get("tokenizer"))
    append_eod = kv.get("append_eod", "1") != "0"

    def docs():
        for text in _iter_documents(inputs):
            ids = tok.encode(text)
            if append_eod:
                ids = ids + [tok.eod_id]
            if ids:
                yield ids

    stats = write_indexed_dataset(prefix, docs())
    # sidecar metadata so the TRAINING loader knows the tokenizer geometry
    # (reference passes these through its tokenizer global; here the corpus
    # is self-describing): consumed by runtime/dataloader.get_data_iterator
    # for eod loss-masking and the MLM mask id
    meta = {"vocab_size": int(tok.vocab_size),
            "eod_id": int(tok.eod_id),
            "mask_id": (int(tok.mask_id) if getattr(tok, "mask_id", None)
                        is not None else None),
            "tokenizer": kv.get("tokenizer") or "byte",
            "documents": stats["documents"],
            "tokens": stats["tokens"]}
    with open(prefix + ".meta.json", "w") as f:
        json.dump(meta, f, indent=2)
    print(f"wrote {prefix}.bin/.idx: {stats['documents']} documents, "
          f"{stats['tokens']} tokens (vocab {tok.vocab_size})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
