"""Offline HF <-> framework checkpoint converter CLI.

Capability parity with the reference's converter entry points
(tools/checkpoint_convert_h2g.py / tools/checkpoint_convert_g2h.py): the
conversion math itself lives in runtime/checkpoint.py (hf_to_params /
params_to_hf, covering gpt2/llama/qwen2/mistral/mixtral/bert/t5 families);
this CLI wraps it in file IO::

    python -m hetu_galvatron_tpu.cli.checkpoint_convert h2g \
        <model.yaml> hf_path=<hf_dir> out=<ckpt_root> [step=0]
    python -m hetu_galvatron_tpu.cli.checkpoint_convert g2h \
        <model.yaml> ckpt=<ckpt_root_or_step_dir> out=<hf_dir>

h2g reads an HF checkpoint directory (*.safetensors preferred, else
pytorch_model*.bin) and writes a framework checkpoint (orbax step dir) that
``cli.train_dist`` resumes from under ANY parallel plan — orbax reshards on
restore, so there is no per-tp-rank slicing step like the reference's
(llama_adapter.py:51-163). g2h restores a step dir and writes
``model.safetensors`` in the HF layout.
"""

from __future__ import annotations

import os
import sys


def _load_hf_state_dict(path: str):
    """HF checkpoint dir -> {name: np.ndarray} (fp32)."""
    import numpy as np

    sd = {}
    if os.path.isdir(path):
        st_files = sorted(f for f in os.listdir(path)
                          if f.endswith(".safetensors"))
        bin_files = sorted(f for f in os.listdir(path)
                           if f.startswith("pytorch_model")
                           and f.endswith(".bin"))
        if st_files:
            from safetensors import safe_open

            for fname in st_files:
                with safe_open(os.path.join(path, fname), framework="np") as f:
                    for k in f.keys():
                        sd[k] = f.get_tensor(k)
        elif bin_files:
            import torch

            for fname in bin_files:
                part = torch.load(os.path.join(path, fname),
                                  map_location="cpu", weights_only=True)
                sd.update(part)
        else:
            raise FileNotFoundError(
                f"no *.safetensors or pytorch_model*.bin under {path}")
    else:
        raise FileNotFoundError(path)

    def to_np(v):
        if hasattr(v, "detach"):  # torch tensor (bf16-safe upcast)
            return v.detach().to("cpu").float().numpy()
        return np.asarray(v)

    return {k: to_np(v) for k, v in sd.items()}


def main(argv=None) -> int:
    from hetu_galvatron_tpu.core.arguments import args_from_cli
    from hetu_galvatron_tpu.utils.hf_config_adapter import resolve_model_config

    argv = list(argv if argv is not None else sys.argv[1:])
    if not argv or argv[0] not in ("h2g", "g2h"):
        print("usage: checkpoint_convert h2g|g2h <model.yaml> key=value ...",
              file=sys.stderr)
        return 2
    direction, argv = argv[0], argv[1:]
    kv = dict(a.split("=", 1) for a in argv if "=" in a and "." not in
              a.split("=", 1)[0])
    passthrough = [a for a in argv if a.split("=", 1)[0] not in
                   ("hf_path", "out", "ckpt", "step")]
    args = args_from_cli(passthrough, mode="train_dist")
    cfg = resolve_model_config(args).model

    if direction == "h2g":
        from hetu_galvatron_tpu.runtime.checkpoint import (
            hf_to_params,
            save_checkpoint,
        )

        sd = _load_hf_state_dict(kv["hf_path"])
        params = hf_to_params(sd, cfg)
        step = int(kv.get("step", 0))
        out = save_checkpoint(kv["out"], step, params)
        print(f"wrote {out}")
        return 0

    from hetu_galvatron_tpu.models.builder import init_causal_lm
    from hetu_galvatron_tpu.runtime.checkpoint import (
        latest_checkpoint,
        load_checkpoint,
        params_to_hf,
    )

    import jax

    ckpt = kv["ckpt"]
    if not os.path.basename(ckpt).startswith("step_"):
        ckpt = latest_checkpoint(ckpt) or ckpt
    target, _ = init_causal_lm(jax.random.key(0), cfg)
    params, _, step = load_checkpoint(ckpt, target)
    sd = params_to_hf(params, cfg)
    os.makedirs(kv["out"], exist_ok=True)
    from safetensors.numpy import save_file

    out_path = os.path.join(kv["out"], "model.safetensors")
    save_file(sd, out_path)
    print(f"wrote {out_path} (step {step}, {len(sd)} tensors)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
