"""Run-summary CLI: ``python -m hetu_galvatron_tpu.cli.summarize
<metrics.jsonl>``.

Reads the JSONL metrics stream a telemetry-enabled run writes
(``observability/sinks.py`` record schema) and prints a human-readable
throughput / MFU / memory / span summary. Counters and gauges carry their
current value at each flush, so the LAST record per (name, labels) is the
end-of-run state; histograms likewise snapshot cumulative percentiles.
"""

from __future__ import annotations

import json
import sys
from typing import Any, Dict, List, Optional, Tuple


def load_records(path: str) -> List[Dict[str, Any]]:
    """Parse the JSONL stream, tolerating a truncated tail: a run killed
    mid-write (OOM/SIGKILL during a sink flush) leaves a partial final
    line, and the post-mortem tool must still summarize everything before
    it. Unparseable lines are counted and warned about, not fatal."""
    out = []
    bad = 0
    with open(path, errors="replace") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                bad += 1
                continue
            # a torn write can also yield VALID JSON that is not a record
            # (e.g. a bare number from a half-flushed line) — a summary
            # must skip it, not crash on rec.get()
            if isinstance(rec, dict):
                out.append(rec)
            else:
                bad += 1
    if bad:
        print(f"warning: skipped {bad} unparseable line(s) in {path} "
              "(truncated by a crashed run?)", file=sys.stderr)
    return out


def last_by_name(records: List[Dict[str, Any]]
                 ) -> Dict[Tuple[str, str, str], Dict[str, Any]]:
    """Last record per (kind, name, labels); later lines win."""
    latest: Dict[Tuple[str, str, str], Dict[str, Any]] = {}
    for r in records:
        if r.get("kind") == "event":
            continue
        key = (r.get("kind", ""), r.get("name", ""),
               json.dumps(r.get("labels") or {}, sort_keys=True))
        latest[key] = r
    return latest


def _fmt(v: Any) -> str:
    if isinstance(v, float):
        if v == 0:
            return "0"
        if abs(v) >= 1000:
            return f"{v:,.0f}"
        if abs(v) >= 1:
            return f"{v:.2f}"
        return f"{v:.4g}"
    return str(v)


def _label_str(labels: str) -> str:
    d = json.loads(labels)
    if not d:
        return ""
    return "{" + ",".join(f"{k}={v}" for k, v in sorted(d.items())) + "}"


def _load_hardware_json(path: str) -> Optional[Dict[str, Any]]:
    """A hardware-profiler bandwidth JSON (one dict of allreduce_size_*
    keys) rather than a JSONL metrics stream — summarize renders its
    bandwidth + fitted α-β table instead."""
    try:
        with open(path) as f:
            obj = json.load(f)
    except (json.JSONDecodeError, OSError):
        return None
    if isinstance(obj, dict) and "kind" not in obj and any(
            k.startswith("allreduce_size_") for k in obj):
        return obj
    return None


def summarize_hardware(cfg: Dict[str, Any], path: str, out=None
                       ) -> Dict[str, Any]:
    """Render a hardware bandwidth JSON: per (group size, consecutiveness)
    the measured bandwidth and, when the profiler fitted them
    (``profile_alpha_beta``), the α (latency ms) / β (MB/ms) pair — the
    latency-aware collective model the search engine prices TP with."""
    out = out or sys.stdout
    w = lambda s="": print(s, file=out)
    w(f"== hardware profile: {path} ==")
    w(f"{'group':<14}{'bw MB/ms':>10}{'alpha ms':>12}{'beta MB/ms':>12}")
    headline: Dict[str, Any] = {"groups": 0, "alpha_beta_groups": 0}
    for key in sorted(cfg):
        if not (key.startswith("allreduce_size_")
                and key.split("_")[-1] in ("0", "1")):
            continue
        parts = key.split("_")  # allreduce_size_{n}_consec_{c}
        n, c = parts[2], parts[4]
        label = f"{n} {'consec' if c == '1' else 'strided'}"
        alpha = cfg.get(f"allreduce_size_{n}_consec_{c}_alpha_ms")
        beta = cfg.get(f"allreduce_size_{n}_consec_{c}_beta_mb_per_ms")
        headline["groups"] += 1
        if alpha is not None and beta is not None:
            headline["alpha_beta_groups"] += 1
            w(f"{label:<14}{_fmt(cfg[key]):>10}{_fmt(alpha):>12}"
              f"{_fmt(beta):>12}")
        else:
            w(f"{label:<14}{_fmt(cfg[key]):>10}{'-':>12}{'-':>12}")
    if not headline["alpha_beta_groups"]:
        w("(no fitted alpha/beta keys: legacy bandwidth-only profile — "
          "the cost model uses the measured latency tables)")
    return headline


def summarize(path: str, out=None) -> Dict[str, Any]:
    """Print the summary; returns the headline numbers (for tests)."""
    out = out or sys.stdout
    w = lambda s="": print(s, file=out)
    hw = _load_hardware_json(path)
    if hw is not None:
        return summarize_hardware(hw, path, out)
    records = load_records(path)
    latest = last_by_name(records)

    def get(kind: str, name: str, labels: str = "{}"
            ) -> Optional[Dict[str, Any]]:
        return latest.get((kind, name, labels))

    headline: Dict[str, Any] = {}
    w(f"== run summary: {path} ({len(records)} records) ==")
    steps = get("counter", "train/steps")
    tokens = get("counter", "train/tokens")
    if steps:
        headline["steps"] = steps["value"]
        w(f"steps            {steps['value']:,.0f}")
    if tokens:
        headline["tokens"] = tokens["value"]
        w(f"tokens           {tokens['value']:,.0f}")
    st = get("histogram", "train/step_time_ms") or \
        get("histogram", "profiler/iter_time_ms")
    if st and st.get("count"):
        headline["step_time_p50_ms"] = st["p50"]
        w(f"step time ms     p50 {_fmt(st['p50'])} | p90 {_fmt(st['p90'])}"
          f" | p99 {_fmt(st['p99'])} | mean {_fmt(st['mean'])}"
          f" (n={st['count']})")
    tps = get("gauge", "train/tokens_per_sec")
    if tps:
        headline["tokens_per_sec"] = tps["value"]
        w(f"tokens/sec       {_fmt(tps['value'])}")
    tfl = get("gauge", "train/model_tflops")
    if tfl:
        w(f"model TFLOP/s    {_fmt(tfl['value'])}")
    mfu = get("gauge", "train/mfu")
    if mfu:
        headline["mfu"] = mfu["value"]
        w(f"MFU              {mfu['value'] * 100:.1f}%")
    for key in ("loss", "grad_norm"):
        g = get("gauge", f"train/{key}")
        if g:
            w(f"final {key:<10} {_fmt(g['value'])}")
    hid = get("gauge", "tp/comm_hidden_frac")
    if hid is not None:
        headline["tp_comm_hidden_frac"] = hid["value"]
        # coverage, not a timing claim: the share of TP collective TRAFFIC
        # running the ring-overlap path (how much of it actually hides
        # depends on the compute/comm balance — cost model's
        # tp_overlap_hidden_frac)
        w(f"TP comm overlapped {hid['value'] * 100:.1f}% "
          "(traffic share on ring-overlap layers)")
    mems = [(lb, r) for (k, n, lb), r in latest.items()
            if k == "gauge" and n == "device/mem_mb"]
    if mems:
        parts = " | ".join(
            f"{json.loads(lb).get('stat', '?')} {_fmt(r['value'])}"
            for lb, r in sorted(mems))
        w(f"device mem MB    {parts}")
    # the plan's predicted comm volume is a one-shot event (constants of
    # the plan; the legacy gauge form is still read for old files)
    plan_ev = [r for r in records if r.get("kind") == "event"
               and r.get("name") == "plan"]
    if plan_ev and "predicted_comm_mb_per_step" in plan_ev[-1].get(
            "data", {}):
        w(f"plan comm MB/step (predicted)  "
          f"{_fmt(float(plan_ev[-1]['data']['predicted_comm_mb_per_step']))}")
    else:
        plan = get("gauge", "plan/comm_total_mb")
        if plan:
            w(f"plan comm MB/step (predicted)  {_fmt(plan['value'])}")

    # -- plan audit calibration table (observability/trace_analysis.py) --
    audits = [r for r in records if r.get("kind") == "event"
              and r.get("name") == "plan_audit"]
    if audits:
        t = audits[-1].get("data", {})
        rows = [r for r in t.get("rows", []) if isinstance(r, dict)]
        headline["audit_components"] = len(rows)
        w()
        w(f"-- plan audit: predicted vs actual (per step, per device; "
          f"{t.get('steps', '?')} steps, {t.get('tracks', '?')} device "
          "tracks) --")
        w(f"{'component':<12}{'pred MB':>10}{'pred ms':>10}{'meas ms':>10}"
          f"{'ratio':>8}{'residual':>10}")
        for r in rows:
            if "measured_frac" in r:  # bubble row
                pf = r.get("predicted_frac")
                w(f"{r.get('component', '?'):<12}{'-':>10}"
                  f"{(_fmt(pf) if pf is not None else '-'):>10}"
                  f"{_fmt(r['measured_frac']):>10}"
                  f"{'-':>8}{'(frac)':>10}")
                continue
            ratio = r.get("ratio")
            if ratio is not None:
                headline[f"audit_ratio_{r.get('component')}"] = ratio
            w(f"{r.get('component', '?'):<12}"
              f"{(_fmt(r['predicted_mb']) if 'predicted_mb' in r else '-'):>10}"
              f"{(_fmt(r['predicted_ms']) if 'predicted_ms' in r else '-'):>10}"
              f"{(_fmt(r['measured_ms']) if 'measured_ms' in r else '-'):>10}"
              f"{(_fmt(ratio) if ratio is not None else '-'):>8}"
              f"{(_fmt(r['residual_ms']) if 'residual_ms' in r else '-'):>10}")
        sd = t.get("step_device_ms")
        if sd is not None:
            headline["audit_step_device_ms"] = sd
            w(f"device busy ms/step  {_fmt(float(sd))}")

    # -- compiled-program cost accounting (cost/* gauges) --
    costs = [(json.loads(lb).get("program", "?"), n.split("/", 1)[1], r)
             for (k, n, lb), r in latest.items()
             if k == "gauge" and n.startswith("cost/")]
    if costs:
        by_prog: Dict[str, Dict[str, float]] = {}
        for prog, stat, r in costs:
            by_prog.setdefault(prog, {})[stat] = r["value"]
        w()
        w("-- program costs (XLA cost_analysis) --")
        w(f"{'program':<24}{'GFLOPs':>10}{'MB accessed':>13}")
        for prog, st in sorted(by_prog.items()):
            gf = st.get("flops", 0.0) / 1e9
            mb = st.get("bytes_accessed", 0.0) / (1024 * 1024)
            w(f"{prog:<24}{_fmt(gf):>10}{_fmt(mb):>13}")

    # -- serving (engine telemetry, serving/engine.py) --
    srv_tps = get("gauge", "serve/tokens_per_sec")
    ttft = get("histogram", "serve/ttft_ms")
    if srv_tps or (ttft and ttft.get("count")):
        w()
        w("-- serving --")
        for key, label in (("serve/requests_submitted", "submitted"),
                           ("serve/requests_completed", "completed"),
                           ("serve/requests_rejected", "rejected"),
                           ("serve/requests_cancelled", "cancelled"),
                           ("serve/requests_timeout", "timed out")):
            c = get("counter", key)
            if c and c["value"]:
                headline[key] = c["value"]
                w(f"requests {label:<12} {c['value']:,.0f}")
        for key, label in (("serve/prefill_tokens", "prefill tokens"),
                           ("serve/decode_tokens", "decode tokens"),
                           ("serve/steps", "engine steps"),
                           ("serve/engine_errors", "engine errors")):
            c = get("counter", key)
            if c and (c["value"] or not key.endswith("errors")):
                w(f"{label:<21} {c['value']:,.0f}")
        # shared-prefix cache (serving/prefix_cache.py)
        ph = get("gauge", "serve/prefix_hit_rate")
        if ph is not None:
            headline["prefix_hit_rate"] = ph["value"]
            cached = get("counter", "serve/prefix_cached_tokens")
            extra = (f" ({cached['value']:,.0f} prompt tokens reused)"
                     if cached and cached["value"] else "")
            w(f"prefix hit rate   {ph['value'] * 100:.1f}%{extra}")
        pb = get("gauge", "serve/prefix_cache_blocks")
        if pb is not None:
            w(f"prefix cache blocks   {_fmt(pb['value'])}")
        # speculative decoding (serving/spec_decode.py): drafted vs
        # emitted — decode_tokens counts what actually reached clients
        sa = get("gauge", "serve/spec_accept_rate")
        if sa is not None:
            headline["spec_accept_rate"] = sa["value"]
            drafted = get("counter", "serve/drafted_tokens")
            accepted = get("counter", "serve/spec_accepted_tokens")
            emitted = get("counter", "serve/decode_tokens")
            parts = [f"spec accept rate  {sa['value'] * 100:.1f}%"]
            if drafted:
                parts.append(f"({drafted['value']:,.0f} drafted, "
                             f"{(accepted or {}).get('value', 0):,.0f} "
                             "accepted"
                             + (f", {emitted['value']:,.0f} emitted)"
                                if emitted else ")"))
            w(" ".join(parts))
        if ttft and ttft.get("count"):
            headline["ttft_p50_ms"] = ttft["p50"]
            w(f"TTFT ms          p50 {_fmt(ttft['p50'])} | p90 "
              f"{_fmt(ttft['p90'])} | p99 {_fmt(ttft['p99'])} "
              f"(n={ttft['count']})")
        itl = get("histogram", "serve/itl_ms")
        if itl and itl.get("count"):
            headline["itl_p50_ms"] = itl["p50"]
            w(f"inter-token ms   p50 {_fmt(itl['p50'])} | p90 "
              f"{_fmt(itl['p90'])} | p99 {_fmt(itl['p99'])} "
              f"(n={itl['count']})")
        if srv_tps:
            headline["serve_tokens_per_sec"] = srv_tps["value"]
            w(f"serve tokens/sec {_fmt(srv_tps['value'])}")
        for key, label in (("serve/queue_depth", "queue depth (end)"),
                           ("serve/active_requests", "active (end)"),
                           ("serve/kv_occupancy", "KV occupancy (end)"),
                           ("serve/kv_blocks_used", "KV blocks (end)"),
                           ("serve/jit_programs", "jit programs")):
            g = get("gauge", key)
            if g is not None:
                w(f"{label:<21} {_fmt(g['value'])}")

    spans = [(json.loads(lb).get("path", "?"), r)
             for (k, n, lb), r in latest.items()
             if k == "histogram" and n == "span_ms" and r.get("count")]
    if spans:
        w()
        w("-- spans (host ms) --")
        w(f"{'path':<24}{'count':>8}{'mean':>10}{'p50':>10}{'p99':>10}")
        for p, r in sorted(spans):
            w(f"{p:<24}{r['count']:>8}{_fmt(r['mean']):>10}"
              f"{_fmt(r['p50']):>10}{_fmt(r['p99']):>10}")

    rest = [((k, n, lb), r) for (k, n, lb), r in sorted(latest.items())
            if k in ("counter", "gauge")
            and not n.startswith(("train/", "device/", "plan/", "serve/",
                                  "tp/", "audit/", "cost/"))]
    if rest:
        w()
        w("-- other counters/gauges --")
        for (k, n, lb), r in rest:
            w(f"{n + _label_str(lb):<40} {_fmt(r['value'])}")

    events = [r for r in records if r.get("kind") == "event"]
    if events:
        w()
        w(f"-- events ({len(events)}) --")
        by_name: Dict[str, int] = {}
        for e in events:
            by_name[e.get("name", "?")] = by_name.get(e.get("name", "?"), 0) + 1
        for n, c in sorted(by_name.items()):
            w(f"{n:<40} {c}")
    return headline


def main(argv=None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    if not argv or argv[0] in ("-h", "--help"):
        print("usage: python -m hetu_galvatron_tpu.cli.summarize "
              "<metrics.jsonl>")
        return 0 if argv else 2
    summarize(argv[0])
    return 0


if __name__ == "__main__":
    sys.exit(main())
