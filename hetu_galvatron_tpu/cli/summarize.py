"""Run-summary CLI: ``python -m hetu_galvatron_tpu.cli.summarize
<metrics.jsonl | flight_*.json> [--timeline [rid|all]]``.

Reads the JSONL metrics stream a telemetry-enabled run writes
(``observability/sinks.py`` record schema) and prints a human-readable
throughput / MFU / memory / span summary. Counters and gauges carry their
current value at each flush, so the LAST record per (name, labels) is the
end-of-run state; histograms likewise snapshot cumulative percentiles.

Request tracing (``serving.trace_requests``, ``observability/events.py``):
when the stream carries per-request lifecycle events the summary adds a
TTFT component breakdown (queue vs prefill vs first-decode, p50/p90/p99
per component — the components are additive, so each request's split sums
to its measured TTFT), an SLO attainment report, and — with
``--timeline`` — per-request event timelines. Corrupt or torn event
records are skipped with a warning, never fatal (the postmortem contract:
this tool runs on files crashed runs left behind).

Also renders flight-recorder dumps (``observability/recorder.py``
``flight_<ts>.json``): reason, exception, the last-N-events ring, and the
metric snapshot; a torn dump degrades to a warning.
"""

from __future__ import annotations

import json
import re
import sys
from typing import Any, Dict, List, Optional, Tuple


def load_records(path: str) -> List[Dict[str, Any]]:
    """Parse the JSONL stream, tolerating a truncated tail: a run killed
    mid-write (OOM/SIGKILL during a sink flush) leaves a partial final
    line, and the post-mortem tool must still summarize everything before
    it. Unparseable lines are counted and warned about, not fatal."""
    out = []
    bad = 0
    with open(path, errors="replace") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                bad += 1
                continue
            # a torn write can also yield VALID JSON that is not a record
            # (e.g. a bare number from a half-flushed line) — a summary
            # must skip it, not crash on rec.get()
            if isinstance(rec, dict):
                out.append(rec)
            else:
                bad += 1
    if bad:
        print(f"warning: skipped {bad} unparseable line(s) in {path} "
              "(truncated by a crashed run?)", file=sys.stderr)
    return out


def last_by_name(records: List[Dict[str, Any]]
                 ) -> Dict[Tuple[str, str, str], Dict[str, Any]]:
    """Last record per (kind, name, labels); later lines win."""
    latest: Dict[Tuple[str, str, str], Dict[str, Any]] = {}
    for r in records:
        if r.get("kind") == "event":
            continue
        key = (r.get("kind", ""), r.get("name", ""),
               json.dumps(r.get("labels") or {}, sort_keys=True))
        latest[key] = r
    return latest


def _fmt(v: Any) -> str:
    if isinstance(v, float):
        if v == 0:
            return "0"
        if abs(v) >= 1000:
            return f"{v:,.0f}"
        if abs(v) >= 1:
            return f"{v:.2f}"
        return f"{v:.4g}"
    return str(v)


def _label_str(labels: str) -> str:
    d = json.loads(labels)
    if not d:
        return ""
    return "{" + ",".join(f"{k}={v}" for k, v in sorted(d.items())) + "}"


def _load_hardware_json(path: str) -> Optional[Dict[str, Any]]:
    """A hardware-profiler bandwidth JSON (one dict of allreduce_size_*
    keys) rather than a JSONL metrics stream — summarize renders its
    bandwidth + fitted α-β table instead."""
    try:
        with open(path) as f:
            obj = json.load(f)
    except (json.JSONDecodeError, OSError):
        return None
    if isinstance(obj, dict) and "kind" not in obj and any(
            k.startswith("allreduce_size_") for k in obj):
        return obj
    return None


def _load_flight_json(path: str) -> Optional[Dict[str, Any]]:
    """A flight-recorder dump (observability/recorder.py) rather than a
    JSONL metrics stream. Sniffs the head of the file for the schema
    marker BEFORE attempting a full parse, so a multi-GB per-token
    metrics stream is not slurped just to decide it isn't a dump. A
    torn/truncated dump fails json parsing and returns None — the caller
    falls through to the line-tolerant JSONL loader, whose
    skip-and-warn path covers it."""
    try:
        with open(path, errors="replace") as f:
            head = f.read(4096)
            if '"flight_recorder"' not in head:
                return None
            obj = json.loads(head + f.read())
    except (json.JSONDecodeError, OSError):
        return None
    if isinstance(obj, dict) and obj.get("kind") == "flight_recorder":
        return obj
    return None


def summarize_flight(obj: Dict[str, Any], path: str, out=None
                     ) -> Dict[str, Any]:
    """Render one flight-recorder dump: the crash reason, the exception
    (if any), the tail of the event ring, and the metric snapshot — a
    self-contained postmortem for a run that is no longer around to ask."""
    out = out or sys.stdout
    w = lambda s="": print(s, file=out)
    headline: Dict[str, Any] = {"flight_reason": obj.get("reason")}
    events = [e for e in obj.get("events", []) if isinstance(e, dict)]
    metrics = [m for m in obj.get("metrics", []) if isinstance(m, dict)]
    w(f"== flight recorder dump: {path} ==")
    w(f"reason           {obj.get('reason', '?')}")
    if obj.get("t"):
        w(f"wall time        {obj['t']:.3f} (pid {obj.get('pid', '?')})")
    exc = obj.get("exception")
    if exc:
        headline["flight_exception"] = exc.get("type")
        w(f"exception        {exc.get('type', '?')}: "
          f"{exc.get('message', '')}")
        tb = (exc.get("traceback") or "").strip().splitlines()
        for line in tb[-8:]:
            w(f"  {line}")
    headline["flight_events"] = len(events)
    w(f"events in ring   {len(events)}")
    for e in events[-16:]:
        d = e.get("data") if isinstance(e.get("data"), dict) else {}
        extra = " ".join(f"{k}={_fmt(v)}" for k, v in sorted(d.items())
                         if k not in ("ev", "seq", "tm"))
        tm = d.get("tm")
        w(f"  {(_fmt(tm) + 'ms').rjust(12) if tm is not None else '?'.rjust(12)}"
          f"  {d.get('ev', e.get('name', '?')):<14} {extra}")
    if metrics:
        w(f"metrics snapshot {len(metrics)} series (last values)")
        for m in metrics[:20]:
            lbl = ("{" + ",".join(f"{k}={v}" for k, v in
                                  sorted((m.get('labels') or {}).items()))
                   + "}" if m.get("labels") else "")
            val = m.get("value", m.get("count"))
            w(f"  {m.get('name', '?') + lbl:<44} {_fmt(val)}")
        if len(metrics) > 20:
            w(f"  ... and {len(metrics) - 20} more")
    return headline


# ---------------------------------------------------------------------------
# request-lifecycle timelines (observability/events.py records)
# ---------------------------------------------------------------------------


def request_timelines(records: List[Dict[str, Any]]
                      ) -> Tuple[Dict[int, List[Dict[str, Any]]], int]:
    """Group ``request`` events by rid, ordered by the stream sequence
    number. Corrupt records (torn writes, missing/mistyped fields) are
    counted and skipped — a crashed run's stream must still summarize.
    Well-formed events WITHOUT a rid (stream-level records like
    ``engine_error``) are not corrupt; they simply belong to no
    timeline. Returns ``(timelines, n_corrupt)``."""
    tl: Dict[int, List[Dict[str, Any]]] = {}
    bad = 0
    for r in records:
        if r.get("kind") != "event" or r.get("name") != "request":
            continue
        d = r.get("data")
        if (not isinstance(d, dict) or "ev" not in d
                or not isinstance(d.get("seq"), (int, float))):
            bad += 1
            continue
        if "rid" not in d:
            continue  # stream-level event (e.g. engine_error), not corrupt
        try:
            tl.setdefault(int(d["rid"]), []).append(d)
        except (TypeError, ValueError):
            bad += 1
    for evs in tl.values():
        evs.sort(key=lambda d: d["seq"])
    return tl, bad


def timeline_complete(evs: List[Dict[str, Any]]) -> bool:
    """A complete, well-ordered lifecycle: starts at ``submit``, ends at
    ``retire``, and the monotonic timestamps never run backwards (the
    acceptance drill pins no orphaned / out-of-order events)."""
    if not evs or evs[0]["ev"] != "submit" or evs[-1]["ev"] != "retire":
        return False
    tms = [e.get("tm") for e in evs if isinstance(e.get("tm"), (int, float))]
    return all(a <= b for a, b in zip(tms, tms[1:]))


def ttft_components(timelines: Dict[int, List[Dict[str, Any]]]
                    ) -> Dict[str, List[float]]:
    """Per-request TTFT component samples from the ``first_token`` events
    (the engine makes the split additive: queue + prefill + decode ==
    ttft)."""
    comp: Dict[str, List[float]] = {"queue": [], "prefill": [],
                                    "first_decode": [], "ttft": []}
    for evs in timelines.values():
        ft = next((e for e in evs if e["ev"] == "first_token"), None)
        if ft is None:
            continue
        try:
            vals = (float(ft["queue_ms"]), float(ft["prefill_ms"]),
                    float(ft["decode_ms"]), float(ft["ttft_ms"]))
        except (KeyError, TypeError, ValueError):
            continue  # corrupt first_token event: skip the whole row
        for key, v in zip(("queue", "prefill", "first_decode", "ttft"),
                          vals):
            comp[key].append(v)
    return comp


def render_timeline(rid: int, evs: List[Dict[str, Any]], w) -> None:
    """One request's event listing, timestamps relative to submit."""
    t0 = evs[0].get("tm") if evs else None
    status = next((e.get("status") for e in reversed(evs)
                   if e["ev"] == "retire"), "?")
    w(f"request {rid} ({len(evs)} events, {status}"
      + ("" if timeline_complete(evs) else ", INCOMPLETE") + "):")
    for e in evs:
        dt = (e["tm"] - t0 if isinstance(e.get("tm"), (int, float))
              and isinstance(t0, (int, float)) else None)
        extra = " ".join(
            f"{k}={_fmt(v)}" for k, v in sorted(e.items())
            if k not in ("ev", "seq", "tm", "rid"))
        w(f"  {('+' + _fmt(dt) + 'ms').rjust(12) if dt is not None else '?'}"
          f"  {e['ev']:<12} {extra}")


def summarize_hardware(cfg: Dict[str, Any], path: str, out=None
                       ) -> Dict[str, Any]:
    """Render a hardware bandwidth JSON: per (group size, consecutiveness)
    the measured bandwidth and, when the profiler fitted them
    (``profile_alpha_beta``), the α (latency ms) / β (MB/ms) pair — the
    latency-aware collective model the search engine prices TP with.
    Per-algorithm/per-level pairs (``profile_alpha_beta_algos``: ring and
    halving-doubling schedules on ICI and the DCN proxy) render as extra
    ``α/β`` columns, "—" where a curve was not fitted."""
    out = out or sys.stdout
    w = lambda s="": print(s, file=out)
    w(f"== hardware profile: {path} ==")
    algo_cols = ("ring_ici", "tree_ici", "ring_dcn", "tree_dcn")
    has_algos = any("_alg_" in k for k in cfg)
    # provenance (observability/calibration.py refit_profile): per-curve
    # {"points": n, "method": "regression"|"scale"} entries keyed
    # "{n}_{c}/flat" or "{n}_{c}/{alg}_{lvl}"
    meta = cfg.get("calibration_meta")
    meta_curves = (meta.get("curves") if isinstance(meta, dict) else
                   None) or {}
    has_prov = bool(meta_curves)
    header = f"{'group':<14}{'bw MB/ms':>10}{'alpha ms':>12}{'beta MB/ms':>12}"
    if has_prov:
        header += f"{'source':>20}{'points':>8}"
    if has_algos:
        header += "".join(f"{c:>18}" for c in algo_cols)
    w(header)
    headline: Dict[str, Any] = {"groups": 0, "alpha_beta_groups": 0,
                                "algo_groups": 0,
                                "calibrated_curves": len(meta_curves)}
    for key in sorted(cfg):
        if not (key.startswith("allreduce_size_")
                and key.split("_")[-1] in ("0", "1")):
            continue
        parts = key.split("_")  # allreduce_size_{n}_consec_{c}
        n, c = parts[2], parts[4]
        label = f"{n} {'consec' if c == '1' else 'strided'}"
        alpha = cfg.get(f"allreduce_size_{n}_consec_{c}_alpha_ms")
        beta = cfg.get(f"allreduce_size_{n}_consec_{c}_beta_mb_per_ms")
        headline["groups"] += 1
        if alpha is not None and beta is not None:
            headline["alpha_beta_groups"] += 1
            line = (f"{label:<14}{_fmt(cfg[key]):>10}{_fmt(alpha):>12}"
                    f"{_fmt(beta):>12}")
        else:
            line = f"{label:<14}{_fmt(cfg[key]):>10}{'-':>12}{'-':>12}"
        if has_prov:
            cm = meta_curves.get(f"{n}_{c}/flat")
            if isinstance(cm, dict):
                line += (f"{'runtime-calibrated':>20}"
                         f"{_fmt(cm.get('points')):>8}")
            elif alpha is not None and beta is not None:
                line += f"{'profiled':>20}{'—':>8}"
            else:
                line += f"{'—':>20}{'—':>8}"
        if has_algos:
            row_has_algo = False
            for col in algo_cols:
                alg, lvl = col.split("_")
                a = cfg.get(f"allreduce_size_{n}_consec_{c}_alg_{alg}"
                            f"_lvl_{lvl}_alpha_ms")
                b = cfg.get(f"allreduce_size_{n}_consec_{c}_alg_{alg}"
                            f"_lvl_{lvl}_beta_mb_per_ms")
                if a is not None and b is not None:
                    row_has_algo = True
                    line += f"{_fmt(a) + '/' + _fmt(b):>18}"
                else:
                    line += f"{'—':>18}"
            if row_has_algo:
                headline["algo_groups"] += 1
        w(line)
    if not headline["alpha_beta_groups"]:
        w("(no fitted alpha/beta keys: legacy bandwidth-only profile — "
          "the cost model uses the measured latency tables)")
    if has_algos:
        w("(per-algorithm columns are alpha/beta of the fitted "
          "ring/halving-doubling schedules per level; the cost model "
          "prices each collective as the min over available curves)")
    if has_prov:
        src = meta.get("source", "runtime-calibrated")
        fp = meta.get("fingerprint")
        w(f"(calibration: {len(meta_curves)} curve(s) {src}"
          + (f" on {fp.get('device')} world={fp.get('world')}"
             if isinstance(fp, dict) else "")
          + "; uncolumned curves: "
          + (", ".join(f"{k}[{v.get('method')},{v.get('points')}pt]"
                       for k, v in sorted(meta_curves.items())
                       if not k.endswith("/flat")) or "none") + ")")
    return headline


def summarize(path: str, out=None,
              timeline: Optional[str] = None) -> Dict[str, Any]:
    """Print the summary; returns the headline numbers (for tests).
    ``timeline`` renders per-request event listings: ``"all"`` or a
    specific rid (string)."""
    out = out or sys.stdout
    w = lambda s="": print(s, file=out)
    hw = _load_hardware_json(path)
    if hw is not None:
        return summarize_hardware(hw, path, out)
    fl = _load_flight_json(path)
    if fl is not None:
        return summarize_flight(fl, path, out)
    records = load_records(path)
    latest = last_by_name(records)

    def get(kind: str, name: str, labels: str = "{}"
            ) -> Optional[Dict[str, Any]]:
        return latest.get((kind, name, labels))

    headline: Dict[str, Any] = {}
    w(f"== run summary: {path} ({len(records)} records) ==")
    steps = get("counter", "train/steps")
    tokens = get("counter", "train/tokens")
    if steps:
        headline["steps"] = steps["value"]
        w(f"steps            {steps['value']:,.0f}")
    if tokens:
        headline["tokens"] = tokens["value"]
        w(f"tokens           {tokens['value']:,.0f}")
    st = get("histogram", "train/step_time_ms") or \
        get("histogram", "profiler/iter_time_ms")
    if st and st.get("count"):
        headline["step_time_p50_ms"] = st["p50"]
        w(f"step time ms     p50 {_fmt(st['p50'])} | p90 {_fmt(st['p90'])}"
          f" | p99 {_fmt(st['p99'])} | mean {_fmt(st['mean'])}"
          f" (n={st['count']})")
    tps = get("gauge", "train/tokens_per_sec")
    if tps:
        headline["tokens_per_sec"] = tps["value"]
        w(f"tokens/sec       {_fmt(tps['value'])}")
    tfl = get("gauge", "train/model_tflops")
    if tfl:
        w(f"model TFLOP/s    {_fmt(tfl['value'])}")
    mfu = get("gauge", "train/mfu")
    if mfu:
        headline["mfu"] = mfu["value"]
        w(f"MFU              {mfu['value'] * 100:.1f}%")
    for key in ("loss", "grad_norm"):
        g = get("gauge", f"train/{key}")
        if g:
            w(f"final {key:<10} {_fmt(g['value'])}")
    hid = get("gauge", "tp/comm_hidden_frac")
    if hid is not None:
        headline["tp_comm_hidden_frac"] = hid["value"]
        # coverage, not a timing claim: the share of TP collective TRAFFIC
        # running the ring-overlap path (how much of it actually hides
        # depends on the compute/comm balance — cost model's
        # tp_overlap_hidden_frac)
        w(f"TP comm overlapped {hid['value'] * 100:.1f}% "
          "(traffic share on ring-overlap layers)")
    mems = [(lb, r) for (k, n, lb), r in latest.items()
            if k == "gauge" and n == "device/mem_mb"]
    if mems:
        parts = " | ".join(
            f"{json.loads(lb).get('stat', '?')} {_fmt(r['value'])}"
            for lb, r in sorted(mems))
        w(f"device mem MB    {parts}")
    # the plan's predicted comm volume is a one-shot event (constants of
    # the plan; the legacy gauge form is still read for old files)
    plan_ev = [r for r in records if r.get("kind") == "event"
               and r.get("name") == "plan"]
    if plan_ev and "predicted_comm_mb_per_step" in plan_ev[-1].get(
            "data", {}):
        w(f"plan comm MB/step (predicted)  "
          f"{_fmt(float(plan_ev[-1]['data']['predicted_comm_mb_per_step']))}")
    else:
        plan = get("gauge", "plan/comm_total_mb")
        if plan:
            w(f"plan comm MB/step (predicted)  {_fmt(plan['value'])}")

    # -- plan audit calibration table (observability/trace_analysis.py) --
    audits = [r for r in records if r.get("kind") == "event"
              and r.get("name") == "plan_audit"]
    if audits:
        t = audits[-1].get("data", {})
        rows = [r for r in t.get("rows", []) if isinstance(r, dict)]
        headline["audit_components"] = len(rows)
        # per-bucket-stage rows of the bucketed hierarchical reduction
        # (trace_analysis audit_plan "dp[hier_rs_b0]"-style components):
        # surfaced in the headline so a bucketed run is recognizable from
        # the one-line summary, rendered like any other audit row below
        n_bucket_rows = sum(
            1 for r in rows
            if re.search(r"\[hier_\w+_b\d+\]", str(r.get("component", ""))))
        if n_bucket_rows:
            headline["audit_hier_bucket_rows"] = n_bucket_rows
        w()
        w(f"-- plan audit: predicted vs actual (per step, per device; "
          f"{t.get('steps', '?')} steps, {t.get('tracks', '?')} device "
          "tracks) --")
        w(f"{'component':<12}{'pred MB':>10}{'pred ms':>10}{'meas ms':>10}"
          f"{'ratio':>8}{'residual':>10}")
        for r in rows:
            if "measured_frac" in r:  # bubble row
                pf = r.get("predicted_frac")
                w(f"{r.get('component', '?'):<12}{'-':>10}"
                  f"{(_fmt(pf) if pf is not None else '-'):>10}"
                  f"{_fmt(r['measured_frac']):>10}"
                  f"{'-':>8}{'(frac)':>10}")
                continue
            ratio = r.get("ratio")
            if ratio is not None:
                headline[f"audit_ratio_{r.get('component')}"] = ratio
            w(f"{r.get('component', '?'):<12}"
              f"{(_fmt(r['predicted_mb']) if 'predicted_mb' in r else '-'):>10}"
              f"{(_fmt(r['predicted_ms']) if 'predicted_ms' in r else '-'):>10}"
              f"{(_fmt(r['measured_ms']) if 'measured_ms' in r else '-'):>10}"
              f"{(_fmt(ratio) if ratio is not None else '-'):>8}"
              f"{(_fmt(r['residual_ms']) if 'residual_ms' in r else '-'):>10}")
        sd = t.get("step_device_ms")
        if sd is not None:
            headline["audit_step_device_ms"] = sd
            w(f"device busy ms/step  {_fmt(float(sd))}")

    # -- self-calibration (observability/calibration.py gauges/events) --
    cal_keys = (("calibration/points_appended", "residual points appended"),
                ("calibration/points_total", "residual points accumulated"),
                ("calibration/curves_fitted", "curves re-fit"),
                ("calibration/drift_score", "drift score"),
                ("calibration/plan_regret_ms", "plan regret ms/step"))
    if any(get("gauge", k) for k, _ in cal_keys):
        w()
        w("-- calibration --")
        for key, label in cal_keys:
            g = get("gauge", key)
            if g is not None:
                headline[key.replace("calibration/", "cal_")] = g["value"]
                w(f"{label:<28} {_fmt(g['value'])}")
        regrets = [r for r in records if r.get("kind") == "event"
                   and r.get("name") == "plan_regret"]
        if regrets:
            d = regrets[-1].get("data", {})
            headline["plan_regret_ms"] = d.get("regret_ms")
            headline["plan_regret_events"] = len(regrets)
            w(f"PLAN REGRET: runner-up #{d.get('best_runner_up')} beats "
              f"the incumbent by {_fmt(d.get('regret_ms'))} ms/step "
              f"({_fmt(100.0 * (d.get('regret_frac') or 0.0))}% > "
              f"{_fmt(100.0 * (d.get('threshold') or 0.0))}% threshold) "
              "under calibrated curves — consider re-searching the plan")

    # -- supervisor timeline (cli/supervise.py events) + RPO table --
    sup_ev = [r for r in records if r.get("kind") == "event"
              and r.get("name") == "supervisor"]
    if sup_ev:
        headline["supervisor_events"] = len(sup_ev)
        t0 = sup_ev[0].get("t")
        w()
        w("-- supervisor timeline (cross-process restarts) --")
        w(f"{'t+s':>8}  {'event':<14}{'attempt':>8}{'code':>6}"
          f"{'commit':>8}{'RPO s':>8}")
        exits = []
        for r in sup_ev:
            d = r.get("data", {})
            if not isinstance(d, dict):
                continue
            rel = (r.get("t") - t0) if isinstance(r.get("t"), (int, float)) \
                and isinstance(t0, (int, float)) else None
            code = d.get("code")
            rpo = d.get("rpo_s")
            w(f"{(_fmt(rel) if rel is not None else '-'):>8}  "
              f"{str(d.get('event', '?')):<14}"
              f"{str(d.get('attempt', '-')):>8}"
              f"{(str(code) if code is not None else '-'):>6}"
              f"{(str(d.get('commit_step')) if d.get('commit_step') is not None else '-'):>8}"
              f"{(_fmt(rpo) if rpo is not None else '-'):>8}")
            if d.get("event") == "child_exit":
                exits.append(d)
        final = sup_ev[-1].get("data", {})
        headline["supervisor_final_event"] = final.get("event")
        headline["supervisor_attempts"] = max(
            (d.get("attempt", 0) for d in exits), default=None)
        if exits:
            # RPO table: wall-clock of un-checkpointed work lost at each
            # child death — the bound ckpt.interval_s buys
            rpos = [d["rpo_s"] for d in exits
                    if isinstance(d.get("rpo_s"), (int, float))]
            nonzero = [d for d in exits if d.get("code")]
            headline["supervisor_child_exits"] = len(exits)
            if rpos:
                headline["supervisor_rpo_max_s"] = max(rpos)
                w(f"child exits      {len(exits)} "
                  f"({len(nonzero)} abnormal) | RPO max "
                  f"{_fmt(max(rpos))}s mean "
                  f"{_fmt(sum(rpos) / len(rpos))}s")
            progressed = sum(1 for d in exits if d.get("progressed"))
            w(f"progress         {progressed}/{len(exits)} exits had "
              "committed new work (restart budget resets)")

    # -- compiled-program cost accounting (cost/* gauges) --
    costs = [(json.loads(lb).get("program", "?"), n.split("/", 1)[1], r)
             for (k, n, lb), r in latest.items()
             if k == "gauge" and n.startswith("cost/")]
    if costs:
        by_prog: Dict[str, Dict[str, float]] = {}
        for prog, stat, r in costs:
            by_prog.setdefault(prog, {})[stat] = r["value"]
        w()
        w("-- program costs (XLA cost_analysis) --")
        w(f"{'program':<24}{'GFLOPs':>10}{'MB accessed':>13}")
        for prog, st in sorted(by_prog.items()):
            gf = st.get("flops", 0.0) / 1e9
            mb = st.get("bytes_accessed", 0.0) / (1024 * 1024)
            w(f"{prog:<24}{_fmt(gf):>10}{_fmt(mb):>13}")

    # -- serving (engine telemetry, serving/engine.py) --
    srv_tps = get("gauge", "serve/tokens_per_sec")
    ttft = get("histogram", "serve/ttft_ms")
    if srv_tps or (ttft and ttft.get("count")):
        w()
        w("-- serving --")
        for key, label in (("serve/requests_submitted", "submitted"),
                           ("serve/requests_completed", "completed"),
                           ("serve/requests_rejected", "rejected"),
                           ("serve/requests_cancelled", "cancelled"),
                           ("serve/requests_timeout", "timed out")):
            c = get("counter", key)
            if c and c["value"]:
                headline[key] = c["value"]
                w(f"requests {label:<12} {c['value']:,.0f}")
        for key, label in (("serve/prefill_tokens", "prefill tokens"),
                           ("serve/decode_tokens", "decode tokens"),
                           ("serve/steps", "engine steps"),
                           ("serve/engine_errors", "engine errors")):
            c = get("counter", key)
            if c and (c["value"] or not key.endswith("errors")):
                w(f"{label:<21} {c['value']:,.0f}")
        # shared-prefix cache (serving/prefix_cache.py)
        ph = get("gauge", "serve/prefix_hit_rate")
        if ph is not None:
            headline["prefix_hit_rate"] = ph["value"]
            cached = get("counter", "serve/prefix_cached_tokens")
            extra = (f" ({cached['value']:,.0f} prompt tokens reused)"
                     if cached and cached["value"] else "")
            w(f"prefix hit rate   {ph['value'] * 100:.1f}%{extra}")
        pb = get("gauge", "serve/prefix_cache_blocks")
        if pb is not None:
            w(f"prefix cache blocks   {_fmt(pb['value'])}")
        # speculative decoding (serving/spec_decode.py): drafted vs
        # emitted — decode_tokens counts what actually reached clients
        sa = get("gauge", "serve/spec_accept_rate")
        if sa is not None:
            headline["spec_accept_rate"] = sa["value"]
            drafted = get("counter", "serve/drafted_tokens")
            accepted = get("counter", "serve/spec_accepted_tokens")
            emitted = get("counter", "serve/decode_tokens")
            parts = [f"spec accept rate  {sa['value'] * 100:.1f}%"]
            if drafted:
                parts.append(f"({drafted['value']:,.0f} drafted, "
                             f"{(accepted or {}).get('value', 0):,.0f} "
                             "accepted"
                             + (f", {emitted['value']:,.0f} emitted)"
                                if emitted else ")"))
            w(" ".join(parts))
        qw = get("histogram", "serve/queue_wait_ms")
        if qw and qw.get("count"):
            headline["queue_wait_p50_ms"] = qw["p50"]
            w(f"queue wait ms    p50 {_fmt(qw['p50'])} | p90 "
              f"{_fmt(qw['p90'])} | p99 {_fmt(qw['p99'])} "
              f"(n={qw['count']})")
        if ttft and ttft.get("count"):
            headline["ttft_p50_ms"] = ttft["p50"]
            w(f"TTFT ms          p50 {_fmt(ttft['p50'])} | p90 "
              f"{_fmt(ttft['p90'])} | p99 {_fmt(ttft['p99'])} "
              f"(n={ttft['count']})")
        itl = get("histogram", "serve/itl_ms")
        if itl and itl.get("count"):
            headline["itl_p50_ms"] = itl["p50"]
            w(f"inter-token ms   p50 {_fmt(itl['p50'])} | p90 "
              f"{_fmt(itl['p90'])} | p99 {_fmt(itl['p99'])} "
              f"(n={itl['count']})")
        # SLO attainment report (serving.slo_ttft_ms / slo_itl_ms knobs)
        slo_parts = []
        for kind, gname, tname in (
                ("TTFT", "serve/slo_ttft_attainment", "serve/slo_ttft_ms"),
                ("ITL", "serve/slo_itl_attainment", "serve/slo_itl_ms")):
            att = get("gauge", gname)
            if att is not None:
                tgt = get("gauge", tname)
                headline[gname] = att["value"]
                slo_parts.append(
                    f"{kind}<={_fmt(tgt['value']) if tgt else '?'}ms "
                    f"attainment {att['value'] * 100:.1f}%")
        if slo_parts:
            w("SLO              " + " | ".join(slo_parts))
        if srv_tps:
            headline["serve_tokens_per_sec"] = srv_tps["value"]
            w(f"serve tokens/sec {_fmt(srv_tps['value'])}")
        for key, label in (("serve/queue_depth", "queue depth (end)"),
                           ("serve/active_requests", "active (end)"),
                           ("serve/kv_occupancy", "KV occupancy (end)"),
                           ("serve/kv_blocks_used", "KV blocks (end)"),
                           ("serve/jit_programs", "jit programs")):
            g = get("gauge", key)
            if g is not None:
                w(f"{label:<21} {_fmt(g['value'])}")

    # -- request-lifecycle tracing (observability/events.py) --
    timelines, bad_ev = request_timelines(records)
    if bad_ev:
        print(f"warning: skipped {bad_ev} corrupt request event(s) in "
              f"{path}", file=sys.stderr)
    # stream-level fatal-engine events carry no rid; surface them here —
    # they are the one record explaining why every request retired
    eng_errs = [r["data"] for r in records
                if r.get("kind") == "event" and r.get("name") == "request"
                and isinstance(r.get("data"), dict)
                and r["data"].get("ev") == "engine_error"]
    if eng_errs:
        headline["engine_error_events"] = len(eng_errs)
        w()
        for e in eng_errs:
            w(f"ENGINE ERROR: {e.get('error', '?')}: "
              f"{e.get('message', '')}")
    if timelines:
        complete = sum(1 for evs in timelines.values()
                       if timeline_complete(evs))
        headline["requests_traced"] = len(timelines)
        headline["timelines_complete"] = complete
        w()
        w(f"-- request traces: {len(timelines)} requests "
          f"({complete} complete timelines) --")
        if complete < len(timelines):
            w(f"   {len(timelines) - complete} INCOMPLETE timeline(s) "
              "(crashed mid-request, or out-of-order events)")
        comp = ttft_components(timelines)
        if comp["ttft"]:
            import numpy as _np

            w(f"TTFT breakdown (n={len(comp['ttft'])}, additive "
              "components)")
            w(f"{'component':<14}{'p50 ms':>10}{'p90 ms':>10}"
              f"{'p99 ms':>10}{'mean ms':>10}")
            for key in ("queue", "prefill", "first_decode", "ttft"):
                arr = _np.asarray(comp[key])
                p50, p90, p99 = _np.percentile(arr, [50, 90, 99])
                headline[f"ttft_{key}_p50_ms"] = float(p50)
                w(f"{key:<14}{_fmt(float(p50)):>10}{_fmt(float(p90)):>10}"
                  f"{_fmt(float(p99)):>10}{_fmt(float(arr.mean())):>10}")
        cold = sum(1 for evs in timelines.values()
                   for e in evs if e["ev"] == "admit" and e.get("cold_retry"))
        if cold:
            w(f"cold retries (prefix-pin livelock fallback)  {cold}")
        if timeline:
            w()
            w("-- request timelines --")
            if timeline == "all":
                for rid in sorted(timelines):
                    render_timeline(rid, timelines[rid], w)
            else:
                try:
                    rid = int(timeline)
                except ValueError:
                    rid = -1
                if rid in timelines:
                    render_timeline(rid, timelines[rid], w)
                else:
                    w(f"(no traced request with rid {timeline})")

    # -- goodput accounting (observability/goodput.py) --
    gp = {n.split("/", 1)[1]: r for (k, n, lb), r in latest.items()
          if k == "gauge" and n.startswith("goodput/")}
    if gp:
        w()
        w("-- goodput --")
        order = ("productive_step_s", "recompile_s", "checkpoint_save_s",
                 "resume_replay_s", "restart_lost_s")
        for key in order + tuple(
                k for k in sorted(gp)
                if k not in order + ("goodput_frac",)):
            r = gp.get(key)
            if r is None:
                continue
            if key.endswith("_s"):
                headline[f"goodput/{key}"] = r["value"]
                w(f"{key:<22} {_fmt(r['value'])} s")
            else:
                w(f"{key:<22} {_fmt(r['value'])}")
        if "goodput_frac" in gp:
            headline["goodput_frac"] = gp["goodput_frac"]["value"]
            w(f"{'goodput':<22} {gp['goodput_frac']['value'] * 100:.1f}%")

    spans = [(json.loads(lb).get("path", "?"), r)
             for (k, n, lb), r in latest.items()
             if k == "histogram" and n == "span_ms" and r.get("count")]
    if spans:
        w()
        w("-- spans (host ms) --")
        w(f"{'path':<24}{'count':>8}{'mean':>10}{'p50':>10}{'p99':>10}")
        for p, r in sorted(spans):
            w(f"{p:<24}{r['count']:>8}{_fmt(r['mean']):>10}"
              f"{_fmt(r['p50']):>10}{_fmt(r['p99']):>10}")

    rest = [((k, n, lb), r) for (k, n, lb), r in sorted(latest.items())
            if k in ("counter", "gauge")
            and not n.startswith(("train/", "device/", "plan/", "serve/",
                                  "tp/", "audit/", "cost/", "goodput/",
                                  "calibration/"))]
    if rest:
        w()
        w("-- other counters/gauges --")
        for (k, n, lb), r in rest:
            w(f"{n + _label_str(lb):<40} {_fmt(r['value'])}")

    events = [r for r in records if r.get("kind") == "event"]
    if events:
        w()
        w(f"-- events ({len(events)}) --")
        by_name: Dict[str, int] = {}
        for e in events:
            by_name[e.get("name", "?")] = by_name.get(e.get("name", "?"), 0) + 1
        for n, c in sorted(by_name.items()):
            w(f"{n:<40} {c}")
    return headline


def main(argv=None) -> int:
    argv = list(argv if argv is not None else sys.argv[1:])
    if not argv or argv[0] in ("-h", "--help"):
        print("usage: python -m hetu_galvatron_tpu.cli.summarize "
              "<metrics.jsonl | flight_*.json> [--timeline [rid|all]]")
        return 0 if argv else 2
    timeline = None
    if "--timeline" in argv:
        i = argv.index("--timeline")
        argv.pop(i)
        # optional value: "all" or a numeric rid — anything else (e.g.
        # the metrics path when the flag comes first) is NOT consumed
        timeline = "all"
        if i < len(argv) and (argv[i] == "all" or argv[i].isdigit()):
            timeline = argv.pop(i)
    if not argv:
        print("usage: python -m hetu_galvatron_tpu.cli.summarize "
              "<metrics.jsonl | flight_*.json> [--timeline [rid|all]]")
        return 2
    summarize(argv[0], timeline=timeline)
    return 0


if __name__ == "__main__":
    sys.exit(main())
