"""Schedule generators shaped to the actual mesh graph.

Every generator returns an UNVERIFIED :class:`~.ir.Schedule`; callers
run :func:`~.verify.verify` before pricing or emission (the check
``--schedules`` pass does exactly that over the whole space). All
generators share one ring-step helper, so the 2D-torus and the
hierarchical schedule are *derived* ring compositions, not bespoke
code:

* :func:`ring_all_reduce` — reduce-scatter ring then all-gather ring,
  ``2(n-1)`` hops of ``1/n`` chunks: bandwidth-optimal, latency-poor.
  Chunk/rank indexing mirrors the hand-built profiler body
  (:mod:`.reference`) exactly, so emission is bit-identical to it.
* :func:`halving_doubling_all_reduce` — recursive halving-doubling:
  ``2·log2(n)`` pairwise exchanges with halving/doubling payloads
  (the second hand-built body, same bit-parity contract).
* :func:`tree_all_reduce` — latency-optimal binomial-tree reduce to a
  root then tree broadcast, ``2·log2(n)`` hops of the WHOLE buffer:
  the α-dominated regime's winner for small gradients.
* :func:`torus2d_all_reduce` — 2D-torus multi-ring: row-ring
  reduce-scatter over column super-chunks, column-ring rs/ag within
  the owned super-chunk, row-ring all-gather back — every hop stays on
  a torus neighbor link.
* :func:`hier_all_reduce` — the hierarchical
  rs-intra / ar-cross / ag-intra schedule derived as ring compositions
  with the cross phase tagged ``dcn`` (slice-major rank order matches
  the flattened ``(HIER_SLICE_AXIS, HIER_HOST_AXIS)`` group).

``SCOPE_PREFIX`` ("dp_sched") prefixes every step scope: the census
marker (:data:`analysis.census.PERMUTE_MARKERS`) and trace attribution
match emitted programs by that substring.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from hetu_galvatron_tpu.collectives.ir import Schedule, Step, Xfer

SCOPE_PREFIX = "dp_sched"


def _slices(n: int, slice_of: Optional[Sequence[int]]) -> Tuple[int, ...]:
    return tuple(slice_of) if slice_of is not None else (0,) * n


def _link(slice_of: Sequence[int], xfers: Sequence[Xfer]) -> str:
    """The step's link tag: the slowest class any of its edges touches."""
    return ("dcn" if any(slice_of[x.src] != slice_of[x.dst]
                         for x in xfers) else "ici")


def _ring_step(ranks: Sequence[int], chunk_groups: Sequence[Tuple[int, ...]],
               t: int, gather: bool) -> List[Xfer]:
    """Hop ``t`` (1-based) of a ring over ``ranks``: position ``p`` sends
    chunk group ``(p - t) % m`` (reduce-scatter) or ``(p - t + 1) % m``
    (all-gather) to position ``p + 1`` — the exact indexing of the
    hand-built profiler ring, generalized to arbitrary rank lists and
    multi-chunk groups (the torus super-chunks)."""
    m = len(ranks)
    off = t - 1 if gather else t
    return [Xfer(ranks[p], ranks[(p + 1) % m],
                 tuple(chunk_groups[(p - off) % m]))
            for p in range(m)]


def _pow2(n: int) -> bool:
    return n >= 2 and (n & (n - 1)) == 0


def ring_all_reduce(n: int, slice_of: Optional[Sequence[int]] = None,
                    name: str = "ring") -> Schedule:
    slc = _slices(n, slice_of)
    groups = [(k,) for k in range(n)]
    steps: List[Step] = []
    for t in range(1, n):
        xf = _ring_step(range(n), groups, t, gather=False)
        steps.append(Step("exchange", _link(slc, xf), t - 1,
                          f"{SCOPE_PREFIX}_{name}_rs{t}", "add",
                          tuple(xf)))
    for t in range(1, n):
        xf = _ring_step(range(n), groups, t, gather=True)
        steps.append(Step("exchange", _link(slc, xf), n - 2 + t,
                          f"{SCOPE_PREFIX}_{name}_ag{t}", "replace",
                          tuple(xf)))
    return Schedule(name=name, kind="all_reduce", n_ranks=n, n_chunks=n,
                    steps=tuple(steps), slice_of=slc,
                    declared_sends_per_rank=2 * (n - 1))


def ring_reduce_scatter(n: int, slice_of: Optional[Sequence[int]] = None,
                        name: str = "ring_rs") -> Schedule:
    """The reduce-scatter half alone: rank ``r`` ends owning chunk ``r``."""
    full = ring_all_reduce(n, slice_of, name=name)
    steps = tuple(s for s in full.steps if s.combine == "add")
    return Schedule(name=name, kind="reduce_scatter", n_ranks=n,
                    n_chunks=n, steps=steps, slice_of=full.slice_of,
                    owner=tuple(range(n)),
                    declared_sends_per_rank=n - 1)


def ring_all_gather(n: int, slice_of: Optional[Sequence[int]] = None,
                    name: str = "ring_ag") -> Schedule:
    """The all-gather half alone, from the ring owner map (chunk r at
    rank r)."""
    full = ring_all_reduce(n, slice_of, name=name)
    steps = tuple(
        Step(s.op, s.link, s.slot - (n - 1), s.scope, s.combine, s.xfers)
        for s in full.steps if s.combine == "replace")
    return Schedule(name=name, kind="all_gather", n_ranks=n, n_chunks=n,
                    steps=steps, slice_of=full.slice_of,
                    owner=tuple(range(n)),
                    declared_sends_per_rank=n - 1)


def halving_doubling_all_reduce(n: int,
                                slice_of: Optional[Sequence[int]] = None,
                                name: str = "tree_hd") -> Schedule:
    if not _pow2(n):
        raise ValueError(f"halving-doubling needs a power-of-two group, "
                         f"got {n}")
    slc = _slices(n, slice_of)
    rounds = n.bit_length() - 1
    # per-rank live chunk window [start, start+size): bit k of the rank
    # selects which half survives round k (bit 0 keeps the low half)
    win = [(0, n) for _ in range(n)]
    steps: List[Step] = []
    slot = 0
    for k in range(rounds):
        xf: List[Xfer] = []
        nxt = list(win)
        for r in range(n):
            p = r ^ (1 << k)
            start, size = win[r]
            half = size // 2
            bit = (r >> k) & 1
            keep = (start, half) if bit == 0 else (start + half, half)
            send_lo = start + half if bit == 0 else start
            xf.append(Xfer(r, p, tuple(range(send_lo, send_lo + half))))
            nxt[r] = keep
        win = nxt
        steps.append(Step("exchange", _link(slc, xf), slot,
                          f"{SCOPE_PREFIX}_{name}_rs{k}", "add",
                          tuple(xf)))
        slot += 1
    for k in range(rounds - 1, -1, -1):
        xf = []
        nxt = list(win)
        for r in range(n):
            p = r ^ (1 << k)
            start, size = win[r]
            xf.append(Xfer(r, p, tuple(range(start, start + size))))
            ps, _ = win[p]
            nxt[r] = (min(start, ps), size * 2)
        win = nxt
        steps.append(Step("exchange", _link(slc, xf), slot,
                          f"{SCOPE_PREFIX}_{name}_ag{k}", "replace",
                          tuple(xf)))
        slot += 1
    return Schedule(name=name, kind="all_reduce", n_ranks=n, n_chunks=n,
                    steps=tuple(steps), slice_of=slc,
                    declared_sends_per_rank=2 * (n - 1))


def tree_all_reduce(n: int, slice_of: Optional[Sequence[int]] = None,
                    name: str = "tree_bcast", root: int = 0) -> Schedule:
    """Binomial-tree reduce to ``root`` then tree broadcast: the whole
    buffer rides every hop (n_chunks = 1), so bytes are n·worse than a
    ring — but only ``2·log2(n)`` α-latencies deep, which wins for
    sub-α-dominated (small) gradients."""
    if not _pow2(n):
        raise ValueError(f"tree reduce/broadcast needs a power-of-two "
                         f"group, got {n}")
    if root != 0:
        raise ValueError("tree_all_reduce only synthesizes root 0")
    slc = _slices(n, slice_of)
    rounds = n.bit_length() - 1
    steps: List[Step] = []
    slot = 0
    for k in range(rounds):
        xf = [Xfer(r, r - (1 << k), (0,)) for r in range(n)
              if r % (1 << (k + 1)) == (1 << k)]
        steps.append(Step("exchange", _link(slc, xf), slot,
                          f"{SCOPE_PREFIX}_{name}_red{k}", "add",
                          tuple(xf)))
        slot += 1
    for k in range(rounds - 1, -1, -1):
        xf = [Xfer(r, r + (1 << k), (0,)) for r in range(n)
              if r % (1 << (k + 1)) == 0]
        steps.append(Step("exchange", _link(slc, xf), slot,
                          f"{SCOPE_PREFIX}_{name}_bc{k}", "replace",
                          tuple(xf)))
        slot += 1
    return Schedule(name=name, kind="all_reduce", n_ranks=n, n_chunks=1,
                    steps=tuple(steps), slice_of=slc, root=root,
                    declared_sends_per_rank=rounds)


def torus2d_all_reduce(rows: int, cols: int,
                       slice_of: Optional[Sequence[int]] = None,
                       name: str = "torus2d") -> Schedule:
    """2D-torus multi-ring: rank (i, c) = i·cols + c. Row rings
    reduce-scatter ``cols`` super-chunks of ``rows`` chunks each, column
    rings reduce-scatter then all-gather the owned super-chunk, row
    rings all-gather back — 2(n-1) chunk-sends per rank, all on torus
    neighbor links."""
    if rows < 2 or cols < 2:
        raise ValueError(f"torus2d needs rows, cols >= 2, got "
                         f"{rows}x{cols}")
    n = rows * cols
    slc = _slices(n, slice_of)
    super_chunk = [tuple(range(j * rows, (j + 1) * rows))
                   for j in range(cols)]
    steps: List[Step] = []
    slot = 0

    def rows_of(i: int) -> List[int]:
        return [i * cols + c for c in range(cols)]

    def col_of(c: int) -> List[int]:
        return [i * cols + c for i in range(rows)]

    for t in range(1, cols):  # row-ring rs over super-chunks
        xf = [x for i in range(rows)
              for x in _ring_step(rows_of(i), super_chunk, t, False)]
        steps.append(Step("exchange", _link(slc, xf), slot,
                          f"{SCOPE_PREFIX}_{name}_rrs{t}", "add",
                          tuple(xf)))
        slot += 1
    for t in range(1, rows):  # column-ring rs inside the owned super-chunk
        xf = [x for c in range(cols)
              for x in _ring_step(col_of(c),
                                  [(c * rows + v,) for v in range(rows)],
                                  t, False)]
        steps.append(Step("exchange", _link(slc, xf), slot,
                          f"{SCOPE_PREFIX}_{name}_crs{t}", "add",
                          tuple(xf)))
        slot += 1
    for t in range(1, rows):  # column-ring ag
        xf = [x for c in range(cols)
              for x in _ring_step(col_of(c),
                                  [(c * rows + v,) for v in range(rows)],
                                  t, True)]
        steps.append(Step("exchange", _link(slc, xf), slot,
                          f"{SCOPE_PREFIX}_{name}_cag{t}", "replace",
                          tuple(xf)))
        slot += 1
    for t in range(1, cols):  # row-ring ag of super-chunks
        xf = [x for i in range(rows)
              for x in _ring_step(rows_of(i), super_chunk, t, True)]
        steps.append(Step("exchange", _link(slc, xf), slot,
                          f"{SCOPE_PREFIX}_{name}_rag{t}", "replace",
                          tuple(xf)))
        slot += 1
    return Schedule(name=name, kind="all_reduce", n_ranks=n, n_chunks=n,
                    steps=tuple(steps), slice_of=slc,
                    topo=(rows, cols),
                    declared_sends_per_rank=2 * (n - 1))


def hier_all_reduce(cross: int, intra: int,
                    name: str = "hier_rings") -> Schedule:
    """The hierarchical rs-intra / ar-cross / ag-intra schedule DERIVED
    from ring compositions: rank = slice·intra + host (slice-major, the
    flattened ``(HIER_SLICE_AXIS, HIER_HOST_AXIS)`` order), ``intra``
    chunks. Intra phases run every slice's ring in the same steps over
    ici; the cross phase walks each chunk's accumulator around its
    slice ring (1-chunk traveling accumulator + return broadcast) over
    dcn — only the 1/intra shard ever touches the seam, exactly the
    shape ``ops/hier_reduce.py`` hand-implements with
    psum_scatter/psum/all_gather."""
    if intra < 2 or cross < 2:
        raise ValueError(f"hier_all_reduce needs cross, intra >= 2, got "
                         f"cross={cross} intra={intra}")
    n = cross * intra
    slc = tuple(r // intra for r in range(n))
    groups = [(h,) for h in range(intra)]
    steps: List[Step] = []
    slot = 0

    def slice_ranks(s: int) -> List[int]:
        return [s * intra + h for h in range(intra)]

    for t in range(1, intra):  # rs-intra (every slice's ring, one step)
        xf = [x for s in range(cross)
              for x in _ring_step(slice_ranks(s), groups, t, False)]
        steps.append(Step("exchange", "ici", slot,
                          f"{SCOPE_PREFIX}_{name}_rs{t}", "add",
                          tuple(xf)))
        slot += 1
    for t in range(1, cross):  # ar-cross: accumulator travels slice t-1 -> t
        xf = [Xfer((t - 1) * intra + h, t * intra + h, (h,))
              for h in range(intra)]
        steps.append(Step("exchange", "dcn", slot,
                          f"{SCOPE_PREFIX}_{name}_arr{t}", "add",
                          tuple(xf)))
        slot += 1
    for t in range(1, cross):  # ar-cross return: broadcast ring back
        xf = [Xfer(((cross - 1 + t - 1) % cross) * intra + h,
                   ((cross + t - 1) % cross) * intra + h, (h,))
              for h in range(intra)]
        steps.append(Step("exchange", "dcn", slot,
                          f"{SCOPE_PREFIX}_{name}_arb{t}", "replace",
                          tuple(xf)))
        slot += 1
    for t in range(1, intra):  # ag-intra
        xf = [x for s in range(cross)
              for x in _ring_step(slice_ranks(s), groups, t, True)]
        steps.append(Step("exchange", "ici", slot,
                          f"{SCOPE_PREFIX}_{name}_ag{t}", "replace",
                          tuple(xf)))
        slot += 1
    # a rank sends intra-1 chunks in each intra ring; in the cross phase
    # a slice sends at most once per direction (twice only when the
    # accumulate and broadcast walks both start from it, i.e. cross > 2)
    return Schedule(name=name, kind="all_reduce", n_ranks=n,
                    n_chunks=intra, steps=tuple(steps), slice_of=slc,
                    topo=(cross, intra),
                    declared_sends_per_rank=2 * (intra - 1)
                    + (2 if cross > 2 else 1))


def synthesize_dp_schedule(name: str, lanes: int,
                           cross: int = 1) -> Schedule:
    """The one schedule family ``name`` synthesized for a ``lanes``-rank
    dp group split over ``cross`` slices — what ``ops/hier_reduce.py``
    builds when a plan records a ``dp_schedule`` it does not
    hand-implement. Raises ValueError (with the family name) when the
    family cannot exist on this group shape; callers gate with
    ``analysis.eligibility.dp_schedule_unsupported_reason``."""
    intra = lanes // max(cross, 1)
    slc = (tuple(r // intra for r in range(lanes))
           if cross > 1 else None)
    if name == "ring":
        return ring_all_reduce(lanes, slc)
    if name == "tree_hd":
        return halving_doubling_all_reduce(lanes, slc)
    if name == "tree_bcast":
        return tree_all_reduce(lanes, slc)
    if name == "torus2d":
        if cross >= 2 and intra >= 2:
            return torus2d_all_reduce(cross, intra, slc)
        if lanes >= 4 and lanes % 2 == 0:
            return torus2d_all_reduce(2, lanes // 2, slc)
        raise ValueError(f"torus2d needs an even dp group >= 4, got "
                         f"{lanes} (cross {cross})")
    if name == "hier_rings":
        return hier_all_reduce(cross, intra)
    raise ValueError(f"unknown dp schedule family {name!r} (expected "
                     f"ring | tree_hd | tree_bcast | torus2d | "
                     f"hier_rings)")


def synthesize_space(n: int, cross: int = 1) -> Dict[str, Schedule]:
    """Every schedule family expressible on an ``n``-rank dp group with
    ``cross`` slices — the space ``check --schedules`` verifies and the
    cost model prices. Keys are the family names the plan JSON records."""
    intra = n // max(cross, 1)
    slc = tuple(r // intra for r in range(n)) if cross > 1 else None
    out: Dict[str, Schedule] = {}
    if n >= 2:
        out["ring"] = ring_all_reduce(n, slc)
    if _pow2(n):
        out["tree_hd"] = halving_doubling_all_reduce(n, slc)
        out["tree_bcast"] = tree_all_reduce(n, slc)
    if cross >= 2 and intra >= 2:
        out["hier_rings"] = hier_all_reduce(cross, intra)
        out["torus2d"] = torus2d_all_reduce(cross, intra, slc)
    elif n >= 4 and n % 2 == 0:
        # single slice: the torus still exists as a 2 x n/2 factoring
        out["torus2d"] = torus2d_all_reduce(2, n // 2, slc)
    return out
