"""α-β pricing of any :class:`~.ir.Schedule`.

The price is a per-link-class fill/drain walk over the wavefront slots
— the same overlap model PR 15's bucketed hierarchical pricing uses:
steps sharing a slot on DIFFERENT link classes overlap (the ICI mesh
and the DCN seam are disjoint hardware), same-class peers serialize on
the link, and each step bills one α plus its per-rank payload over the
link's β. The per-LINK curves are not profiled directly; they are
inverted out of the fitted per-algorithm ring curves
(``hardware_profiler.profile_alpha_beta_algos``): a fitted ring
all-reduce over ``m`` ranks is ``2(m-1)`` hops of ``1/m`` payload, so

    T_fit(mb) = α_fit + mb/β_fit  =  2(m-1)·α_link + 2(m-1)·mb/(m·β_link)
    ⇒  α_link = α_fit / (2(m-1)),   β_link = β_fit · 2(m-1)/m

which makes the pricer EXACT on the ring schedule it was inverted from
and consistent across every synthesized shape. Calibrated profiles
(PR 16) re-fit the same curve namespace, so schedule prices track
production traces with no new plumbing.
"""

from __future__ import annotations

from typing import Dict, Mapping, Optional, Tuple

from hetu_galvatron_tpu.collectives.ir import Schedule

LinkCurves = Dict[str, Tuple[float, float]]  # class -> (α ms, β MB/ms)


def invert_ring_fit(alpha_fit: float, beta_fit: float,
                    m: int) -> Tuple[float, float]:
    """Per-hop link (α, β) from a fitted ``m``-rank ring all-reduce
    curve (docstring math)."""
    if m < 2:
        raise ValueError(f"ring fit inversion needs m >= 2, got {m}")
    hops = 2 * (m - 1)
    return alpha_fit / hops, beta_fit * hops / m


def link_curves_from_algos(
        algos: Mapping[str, Mapping[str, Tuple[float, float]]],
        n_ici: int, n_dcn: int = 1) -> LinkCurves:
    """ici/dcn link curves inverted from the profiled per-algorithm
    tables (``CostContext.alpha_beta_algos`` layout:
    ``"{size}_{consec}" -> {"{alg}_{lvl}": (α, β)}``). The ici link
    prefers the ring fit at exactly ``n_ici`` consecutive ranks, the dcn
    link the strided/multi-host fit at ``n_dcn``; when the exact size
    was not profiled, the nearest profiled size at that level is
    inverted instead (the link is the same wire — only the fit's hop
    count changes, which the inversion divides back out)."""
    out: LinkCurves = {}
    for lvl, consec, want in (("ici", 1, n_ici), ("dcn", 0, n_dcn)):
        if want < 2:
            continue
        best: Optional[Tuple[int, float, float]] = None
        for key, table in algos.items():
            try:
                size_s, consec_s = key.rsplit("_", 1)
                size = int(size_s)
            except ValueError:
                continue
            if int(consec_s) != consec and lvl == "dcn":
                # dcn groups may also be profiled consec=1 on true
                # multi-host meshes; accept either, prefer consec match
                pass
            pair = table.get(f"ring_{lvl}")
            if pair is None:
                continue
            rank = abs(size - want)
            if best is None or rank < abs(best[0] - want):
                best = (size, pair[0], pair[1])
        if best is not None:
            out[lvl] = invert_ring_fit(best[1], best[2], best[0])
    return out


def price_schedule_ms(sched: Schedule, payload_mb: float,
                      curves: Mapping[str, Tuple[float, float]]
                      ) -> Optional[float]:
    """Milliseconds for one execution of ``sched`` moving a
    ``payload_mb``-MB per-device buffer, or None when a link class the
    schedule uses has no curve. Fill/drain over wavefront slots: per
    slot, same-class steps serialize (sum), classes overlap (max).

    ICI bandwidth bills × the torus hop distance
    (``Schedule.hop_distance``): the ICI mesh is nearest-neighbour
    links, so a stride-``2^k`` halving-doubling exchange occupies
    ``2^k`` links and its translation-invariant all-ranks pattern puts
    ``2^k`` messages on every link — which is exactly why the ring is
    bandwidth-optimal on a torus and the tree families only win the
    α-dominated small-payload regime. dcn exchanges are switch-routed:
    distance 1 always."""
    if sched.n_chunks < 1:
        return None
    chunk_mb = payload_mb / sched.n_chunks
    slots: Dict[int, Dict[str, float]] = {}
    for step in sched.steps:
        if step.op != "exchange" or not step.xfers:
            continue
        pair = curves.get(step.link)
        if pair is None:
            return None
        alpha, beta = pair
        if step.link == "ici":
            load = max(len(x.chunks) * sched.hop_distance(x.src, x.dst)
                       for x in step.xfers)
        else:
            load = sched.step_max_chunks_sent(step)
        mb = load * chunk_mb
        per = slots.setdefault(step.slot, {})
        per[step.link] = per.get(step.link, 0.0) + alpha + mb / beta
    return sum(max(per.values()) for per in slots.values()) if slots \
        else 0.0


def price_space(space: Mapping[str, Schedule], payload_mb: float,
                curves: Mapping[str, Tuple[float, float]]
                ) -> Dict[str, float]:
    """Price every schedule in a synthesized space; families a missing
    curve cannot price are dropped (min-over-curves never invents a
    number)."""
    out: Dict[str, float] = {}
    for name, sched in space.items():
        ms = price_schedule_ms(sched, payload_mb, curves)
        if ms is not None:
            out[name] = ms
    return out
