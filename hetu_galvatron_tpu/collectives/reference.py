"""The canonical HAND-BUILT collective bodies.

These are the two explicit algorithm-shaped all-reduce programs the
repo has carried since PR 13 in
``core/profiler/hardware_profiler._algo_allreduce_ms`` — the ring
(reduce-scatter ring then all-gather ring) and recursive
halving-doubling. They live here now so the profiler and the
bit-parity contract share ONE implementation: the emitter's lowering of
the *synthesized* ring / halving-doubling schedules is pinned
bit-identical to these bodies (same hop order, same add association —
IEEE addition is commutative, so only the association tree matters).

``axis`` may be one axis name or a tuple of names (ppermute and
axis_index both flatten a tuple row-major, which is how the emitted
programs run over the regrouped ``(HIER_SLICE_AXIS, HIER_HOST_AXIS)``
dp group).
"""

from __future__ import annotations

from typing import Callable, Tuple, Union

import jax
import jax.numpy as jnp

Axis = Union[str, Tuple[str, ...]]


def handbuilt_allreduce_body(alg: str, n: int,
                             axis: Axis) -> Callable:
    """The hand-built all-reduce body for ``alg`` over an ``n``-rank
    group on ``axis``: a function of one flat per-device vector (length
    divisible by ``n`` for ring, by 2 per halving round for tree),
    returning the group sum — to be called inside a full-manual
    shard_map over ``axis``."""
    if n < 2 or (n & (n - 1)):
        raise ValueError(f"algorithm schedules need a power-of-two "
                         f"group, got {n}")

    if alg == "ring":
        def body(v):
            r = jax.lax.axis_index(axis)
            c = v.shape[0] // n
            chunks = v.reshape(n, c)
            perm = [(i, (i + 1) % n) for i in range(n)]
            # reduce-scatter ring: the accumulator for chunk k starts
            # at rank (k+1)%n and collects each rank's share en route
            acc = None
            for t in range(n):
                k = (r - 1 - t) % n
                part = jnp.take(chunks, k, axis=0)
                acc = part if acc is None else (
                    jax.lax.ppermute(acc, axis, perm) + part)
            # all-gather ring: rotate the owned chunk n-1 hops
            out = jnp.zeros((n, c), v.dtype)
            cur = acc
            for t in range(n):
                k = (r - t) % n
                out = jax.lax.dynamic_update_index_in_dim(out, cur, k, 0)
                if t < n - 1:
                    cur = jax.lax.ppermute(cur, axis, perm)
            return out.reshape(-1)
        return body

    if alg == "tree":
        rounds = n.bit_length() - 1

        def body(v):
            r = jax.lax.axis_index(axis)
            cur = v
            # recursive halving reduce-scatter: round k exchanges half
            # the live payload with the rank at distance 2^k
            for k in range(rounds):
                perm = [(i, i ^ (1 << k)) for i in range(n)]
                half = cur.shape[0] // 2
                bit = (r >> k) & 1
                lo, hi = cur[:half], cur[half:]
                send = jnp.where(bit == 0, hi, lo)
                recv = jax.lax.ppermute(send, axis, perm)
                cur = jnp.where(bit == 0, lo, hi) + recv
            # recursive doubling all-gather: reverse rounds, payload
            # doubling back to full size
            for k in range(rounds - 1, -1, -1):
                perm = [(i, i ^ (1 << k)) for i in range(n)]
                bit = (r >> k) & 1
                recv = jax.lax.ppermute(cur, axis, perm)
                cur = jnp.where(bit == 0,
                                jnp.concatenate([cur, recv]),
                                jnp.concatenate([recv, cur]))
            return cur
        return body

    raise ValueError(f"unknown collective algorithm {alg!r} (ring | tree)")
