"""Collective-schedule compiler: a chunk/step IR that synthesizes,
verifies, prices, and emits mesh-shaped collectives.

The repo hand-built three collective schedules (ring rs/ag, recursive
halving-doubling, bucketed wavefront rs/ar/ag) and priced them
min-over-curves; this package is the generalization the ROADMAP asked
for (GC3's chunk-step collective IR, arxiv 2201.11840; "The Big
Send-off"'s topology-shaped synthesis, arxiv 2504.18658):

* :mod:`.ir` — ``Schedule`` / ``Step`` / ``Xfer``: chunks of a logical
  buffer moved by ppermute exchanges, each step tagged with a link
  class (``ici`` / ``dcn``) and a wavefront slot.
* :mod:`.synthesize` — generators shaped to the actual mesh graph:
  ring rs/ag, recursive halving-doubling, 2D-torus multi-ring,
  latency-optimal binary trees, and the hierarchical
  rs-intra/ar-cross/ag-intra schedule *derived* by embedding ring
  sub-schedules instead of bespoke code.
* :mod:`.verify` — a static verifier (reduction completeness, per-rank
  count/byte-exactness, link-class legality, step-order deadlock
  freedom) that rejects broken schedules diagnostically.
* :mod:`.emit` — lowers a verified ``Schedule`` to one full-manual
  shard_map body with per-step ``named_scope`` markers, so the census /
  flow passes and trace attribution consume emitted programs unchanged.
* :mod:`.pricing` — α-β pricing of any ``Schedule`` (per-link-class
  fill/drain over wavefront slots) on the calibrated curve plumbing.
* :mod:`.reference` — the canonical HAND-BUILT ring / halving-doubling
  bodies (lifted from the hardware profiler); the emitted programs are
  pinned bit-identical to them.
"""

from hetu_galvatron_tpu.collectives.ir import (  # noqa: F401
    Schedule,
    ScheduleError,
    Step,
    Xfer,
)
