"""Static schedule verifier.

``verify(schedule)`` simulates the schedule rank-by-rank, chunk-by-chunk
— each (rank, chunk) buffer slot tracks the multiset of original
contributions it currently holds — and raises :class:`ScheduleError`
with a diagnostic that NAMES the offending step (never a traceback) on
the first violation of:

* **structure** — kinds / link classes / combine modes / chunk ids in
  range, owner map present where the kind needs one;
* **deadlock freedom** — wavefront slots non-decreasing in step order
  (a later step on an earlier slot is a cyclic wavefront), and each
  exchange a partial permutation (one send and one receive per rank per
  step — the contract ``lax.ppermute`` executes without deadlock);
* **link legality** — an ``ici``-tagged step may not carry a transfer
  that crosses slices (a ``dcn`` tag admits both: the slower class
  bounds the step);
* **reduction sanity** — a rank never sends a chunk slot it holds
  nothing for, and an ``add`` never combines two copies of the same
  original contribution (duplicate reduction breaks the sum);
* **completeness** — the final state the kind promises: every rank
  holds every chunk summed exactly once (``all_reduce``), the owner
  holds its chunk exactly once (``reduce_scatter``), everyone holds the
  owners' finished chunks (``all_gather``), the root holds the full sum
  (``reduce``), everyone holds the root's chunks (``broadcast``);
* **count/byte-exactness** — the simulated per-rank chunk-send totals
  match the generator's ``declared_sends_per_rank`` budget, so a
  schedule that under-declares its bytes (the pricer would underbill
  it) is rejected even when the data movement itself is complete.

The broken-schedule corpus in ``tests/collectives`` mutates healthy
schedules along each of these axes and pins the diagnostics.
"""

from __future__ import annotations

from collections import Counter
from typing import Dict, List, Tuple

from hetu_galvatron_tpu.collectives.ir import (
    COMBINES,
    KINDS,
    LINK_CLASSES,
    Schedule,
    ScheduleError,
    Step,
)

State = List[List[Counter]]  # state[rank][chunk] -> Counter of contributors


def _fail(i: int, step: Step, msg: str) -> None:
    raise ScheduleError(f"step {i} ({step.scope!r}, slot {step.slot}, "
                        f"{step.link}): {msg}")


def _initial_state(sched: Schedule) -> State:
    n, c = sched.n_ranks, sched.n_chunks
    state: State = [[Counter() for _ in range(c)] for _ in range(n)]
    if sched.kind in ("all_reduce", "reduce_scatter", "reduce"):
        # every rank starts holding its own partial of every chunk
        for r in range(n):
            for k in range(c):
                state[r][k][r] = 1
    elif sched.kind == "all_gather":
        # owners start holding their finished chunk ("done" marks a
        # fully-reduced value; gathering must not re-add it)
        for k, o in enumerate(sched.owner or ()):
            state[o][k]["done"] = 1
    elif sched.kind == "broadcast":
        for k in range(sched.n_chunks):
            state[sched.root][k]["done"] = 1
    return state


def _full(sched: Schedule) -> Counter:
    if sched.kind in ("all_gather", "broadcast"):
        return Counter({"done": 1})
    return Counter({r: 1 for r in range(sched.n_ranks)})


def _check_structure(sched: Schedule) -> None:
    if sched.kind not in KINDS:
        raise ScheduleError(f"schedule {sched.name!r}: unknown kind "
                            f"{sched.kind!r} (expected one of {KINDS})")
    if len(sched.slice_of) != sched.n_ranks:
        raise ScheduleError(
            f"schedule {sched.name!r}: slice_of has {len(sched.slice_of)} "
            f"entries for {sched.n_ranks} ranks")
    needs_owner = sched.kind in ("reduce_scatter", "all_gather")
    if needs_owner and (sched.owner is None
                        or len(sched.owner) != sched.n_chunks):
        raise ScheduleError(
            f"schedule {sched.name!r}: kind {sched.kind} needs an owner "
            f"map covering all {sched.n_chunks} chunks")


def _apply_exchange(sched: Schedule, i: int, step: Step,
                    state: State) -> None:
    srcs: Dict[int, int] = {}
    dsts: Dict[int, int] = {}
    recvs: List[Tuple[int, Tuple[int, ...], List[Counter]]] = []
    for x in step.xfers:
        for r, what in ((x.src, "rank"), (x.dst, "rank")):
            if not (0 <= r < sched.n_ranks):
                _fail(i, step, f"{what} {r} out of range "
                               f"[0, {sched.n_ranks})")
        if x.src in srcs:
            _fail(i, step, f"rank {x.src} is the source of two transfers "
                           f"in one exchange (not a partial permutation; "
                           f"one ppermute cannot carry both)")
        if x.dst in dsts:
            _fail(i, step, f"rank {x.dst} is the destination of two "
                           f"transfers in one exchange (not a partial "
                           f"permutation)")
        srcs[x.src] = dsts[x.dst] = 1
        if step.link == "ici" and sched.link_of(x.src, x.dst) == "dcn":
            _fail(i, step, f"transfer {x.src}->{x.dst} crosses slices "
                           f"({sched.slice_of[x.src]} -> "
                           f"{sched.slice_of[x.dst]}) but the step is "
                           f"tagged ici — link-class violation")
        payload: List[Counter] = []
        for k in x.chunks:
            if not (0 <= k < sched.n_chunks):
                _fail(i, step, f"transfer {x.src}->{x.dst} names chunk "
                               f"{k} out of range [0, {sched.n_chunks})")
            if not state[x.src][k]:
                _fail(i, step, f"rank {x.src} sends chunk {k} but holds "
                               f"no contribution for it")
            payload.append(Counter(state[x.src][k]))
        recvs.append((x.dst, x.chunks, payload))
    # apply all receives after all sends (a ppermute is bulk-synchronous)
    for dst, chunks, payload in recvs:
        for k, contrib in zip(chunks, payload):
            if step.combine == "add":
                dup = set(state[dst][k]) & set(contrib)
                if dup:
                    who = sorted(map(str, dup))[0]
                    _fail(i, step,
                          f"duplicate reduction: rank {dst} chunk {k} "
                          f"already holds the contribution of {who} and "
                          f"the add from rank "
                          f"{[x.src for x in step.xfers if x.dst == dst][0]}"
                          f" delivers it again")
                state[dst][k] = state[dst][k] + contrib
            else:
                state[dst][k] = contrib


def _apply_copy(sched: Schedule, i: int, step: Step, state: State) -> None:
    for (r, a, b) in step.copies:
        for k in (a, b):
            if not (0 <= k < sched.n_chunks):
                _fail(i, step, f"copy on rank {r} names chunk {k} out of "
                               f"range [0, {sched.n_chunks})")
        if not state[r][a]:
            _fail(i, step, f"rank {r} copies chunk {a} it holds nothing "
                           f"for")
        state[r][b] = Counter(state[r][a])


def _check_final(sched: Schedule, state: State) -> None:
    full = _full(sched)

    def want(r: int, k: int, where: str) -> None:
        got = state[r][k]
        if got == full:
            return
        missing = sorted(map(str, set(full) - set(got)))
        extra = {str(q): n for q, n in got.items() if n > full.get(q, 0)}
        if missing:
            raise ScheduleError(
                f"schedule {sched.name!r}: incomplete {sched.kind} — "
                f"{where}: rank {r} chunk {k} is missing the "
                f"contribution(s) of {missing[:4]} (a dropped chunk "
                f"never arrived)")
        raise ScheduleError(
            f"schedule {sched.name!r}: over-reduced {sched.kind} — "
            f"{where}: rank {r} chunk {k} holds extra copies {extra}")

    if sched.kind in ("all_reduce", "all_gather", "broadcast"):
        for r in range(sched.n_ranks):
            for k in range(sched.n_chunks):
                want(r, k, "every rank must finish holding every chunk")
    elif sched.kind == "reduce_scatter":
        for k, o in enumerate(sched.owner or ()):
            want(o, k, "the owner must finish holding its chunk")
    elif sched.kind == "reduce":
        for k in range(sched.n_chunks):
            want(sched.root, k, "the root must finish holding every chunk")


def _check_budget(sched: Schedule) -> None:
    if sched.declared_sends_per_rank is None:
        return
    per = sched.sends_per_rank()
    worst = max(per.values(), default=0)
    if worst != sched.declared_sends_per_rank:
        r = max(per, key=lambda q: per[q])
        raise ScheduleError(
            f"schedule {sched.name!r}: count/byte mismatch — the "
            f"schedule declares {sched.declared_sends_per_rank} chunk "
            f"sends per rank but rank {r} actually sends {per[r]} "
            f"(an under-declared budget would underbill the pricer)")


def verify(sched: Schedule) -> Schedule:
    """Raise :class:`ScheduleError` with a step-naming diagnostic if the
    schedule is broken; return it unchanged when clean (so call sites
    can write ``emit(verify(sched), ...)``)."""
    _check_structure(sched)
    state = _initial_state(sched)
    last_slot = None
    for i, step in enumerate(sched.steps):
        if step.op not in ("exchange", "copy"):
            _fail(i, step, f"unknown op {step.op!r}")
        if step.link not in LINK_CLASSES:
            _fail(i, step, f"unknown link class {step.link!r}")
        if step.combine not in COMBINES:
            _fail(i, step, f"unknown combine {step.combine!r}")
        if last_slot is not None and step.slot < last_slot:
            _fail(i, step,
                  f"wavefront slot {step.slot} after a step at slot "
                  f"{last_slot} — step order is cyclic/non-monotone "
                  f"(deadlock: a ppermute cannot wait on a later one)")
        last_slot = step.slot
        if step.op == "exchange":
            _apply_exchange(sched, i, step, state)
        else:
            _apply_copy(sched, i, step, state)
    _check_final(sched, state)
    _check_budget(sched)
    return sched
