"""The chunk/step collective IR.

A ``Schedule`` moves equal-sized **chunks** of one logical per-device
buffer between **ranks** over a sequence of **steps**:

* ``exchange`` — a bulk send/recv realized as ONE ``lax.ppermute``: a
  set of :class:`Xfer` edges, each moving a tuple of chunk ids from one
  rank to another, combined at the destination by ``add`` (reduction)
  or ``replace`` (gather/broadcast). Within a step the edges must form
  a partial permutation (no rank sends twice, none receives twice) —
  that is exactly what ppermute can express deadlock-free.
* ``copy`` — rank-local chunk moves (no communication).

Each step carries a **link class** (``ici`` — intra-slice fast links,
``dcn`` — the cross-slice seam) and a **wavefront slot**: steps sharing
a slot are emitted as overlappable peers (disjoint link classes run
concurrently; same-class peers serialize on the link), the same
fill/drain model PR 15's bucketed hierarchical schedule prices.

The IR is deliberately *dumb*: plain frozen dataclasses, no methods
that mutate, every structural fact explicit — so the static verifier
(:mod:`.verify`) can simulate a schedule rank-by-rank and the emitter
(:mod:`.emit`) can lower it with constant index tables.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Dict, Optional, Tuple

LINK_CLASSES = ("ici", "dcn")
KINDS = ("all_reduce", "reduce_scatter", "all_gather", "reduce",
         "broadcast")
COMBINES = ("add", "replace")


class ScheduleError(ValueError):
    """A schedule that is structurally broken; ``str(e)`` is the
    diagnostic (always names the offending step — never a traceback)."""


@dataclass(frozen=True)
class Xfer:
    """One edge of an exchange: ``src`` sends its current copy of
    ``chunks`` (global chunk ids, in payload order) to ``dst``."""

    src: int
    dst: int
    chunks: Tuple[int, ...]


@dataclass(frozen=True)
class Step:
    """One IR step. ``scope`` is the ``jax.named_scope`` marker the
    emitter stamps on the step's collective, so trace attribution and
    the census bill it; ``slot`` is the wavefront position used by the
    pricer and by the deadlock-order check."""

    op: str  # "exchange" | "copy"
    link: str  # "ici" | "dcn"
    slot: int
    scope: str
    combine: str = "add"  # exchange only: "add" | "replace"
    xfers: Tuple[Xfer, ...] = ()
    # copy only: (rank, src_chunk, dst_chunk) triples
    copies: Tuple[Tuple[int, int, int], ...] = ()


@dataclass(frozen=True)
class Schedule:
    """The unit of exchange between synthesis, verification, pricing and
    emission.

    ``slice_of`` maps each rank to its slice id — two ranks on the same
    slice talk over ``ici`` links, across slices over ``dcn`` (the link
    legality the verifier enforces). ``owner`` (reduce_scatter /
    all_gather kinds) maps each chunk to the rank that holds it fully
    reduced at the rs→ag boundary. ``declared_sends_per_rank`` is the
    generator's own per-rank traffic budget in CHUNK units; the verifier
    cross-checks the simulated per-rank send count against it, so a
    generator that under-declares its bytes is rejected."""

    name: str
    kind: str
    n_ranks: int
    n_chunks: int
    steps: Tuple[Step, ...]
    slice_of: Tuple[int, ...]
    owner: Optional[Tuple[int, ...]] = None
    root: int = 0
    declared_sends_per_rank: Optional[int] = None
    # physical torus shape the rank ids flatten from, row-major (the ICI
    # mesh is a torus of nearest-neighbour links): pricing bills an
    # intra-slice message by its ring hop distance — a 2^k-stride
    # halving-doubling exchange occupies 2^k links, a ring hop one. None
    # = a 1D nearest-neighbour ring of all ranks
    topo: Optional[Tuple[int, ...]] = None
    meta: Dict[str, str] = field(default_factory=dict)

    # -- structural helpers (no simulation; verify.py does that) ---------

    def link_of(self, a: int, b: int) -> str:
        return "ici" if self.slice_of[a] == self.slice_of[b] else "dcn"

    def hop_distance(self, a: int, b: int) -> int:
        """ICI link hops between ranks ``a`` and ``b`` on the physical
        torus (``topo``; per-dim ring distance, summed — the links a
        message traverses and therefore occupies). dcn exchanges are
        switch-routed and always distance 1 (the pricer never calls this
        for them)."""
        shape = self.topo if self.topo else (self.n_ranks,)
        d = 0
        for size in reversed(shape):
            ca, cb = a % size, b % size
            a //= size
            b //= size
            dd = abs(ca - cb)
            d += min(dd, size - dd)
        if a != b:  # topo smaller than the rank space (leftover differs)
            d = max(d, 1)
        return d

    @property
    def n_exchanges(self) -> int:
        """ppermute count of the emitted program — one per exchange
        step (the census's ``ppermute_dp`` prediction)."""
        return sum(1 for s in self.steps if s.op == "exchange")

    def padded_elems(self, local_elems: int) -> int:
        """Payload element count after the emitter's zero-pad to a whole
        number of equal chunks."""
        c = max(self.n_chunks, 1)
        return -(-int(local_elems) // c) * c

    def chunk_elems(self, local_elems: int) -> int:
        return self.padded_elems(local_elems) // max(self.n_chunks, 1)

    def step_max_chunks_sent(self, step: Step) -> int:
        """Largest per-rank chunk count sent in one step — the payload a
        single link carries, which is what α-β pricing bills."""
        sent: Dict[int, int] = {}
        for x in step.xfers:
            sent[x.src] = sent.get(x.src, 0) + len(x.chunks)
        return max(sent.values(), default=0)

    def sends_per_rank(self) -> Dict[int, int]:
        """Simulated per-rank chunk-send totals over the whole schedule
        (the count/byte-exactness side of verification)."""
        out = {r: 0 for r in range(self.n_ranks)}
        for s in self.steps:
            for x in s.xfers:
                out[x.src] = out.get(x.src, 0) + len(x.chunks)
        return out

    def exchange_bytes_per_rank(self, local_elems: int,
                                elem_bytes: int = 4) -> float:
        """Bytes one rank sends over the whole schedule, at the padded
        chunk size for ``local_elems`` payload elements — the flow
        pass's per-schedule byte prediction."""
        cb = self.chunk_elems(local_elems) * elem_bytes
        per = self.sends_per_rank()
        return float(max(per.values(), default=0) * cb)

    def with_scope_prefix(self, prefix: str) -> "Schedule":
        return replace(self, steps=tuple(
            replace(s, scope=f"{prefix}{s.scope}") for s in self.steps))
