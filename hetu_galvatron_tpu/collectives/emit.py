"""Lower a verified :class:`~.ir.Schedule` to a shard_map body.

The lowering is table-driven: for every exchange step the emitter
precomputes constant per-rank int32 tables — which chunk slots each
rank sends, which slots the received payload lands in, and whether the
rank participates — and the body gathers its own row with
``jax.lax.axis_index``. Each step is exactly ONE ``lax.ppermute`` under
the step's ``jax.named_scope`` marker, so the census counts it, trace
attribution bills it, and the flow pass weighs its bytes, all through
the machinery the hand-built kernels already use.

Combine semantics are ``new = recv + cur`` for ``add`` (the same
operand order as the hand-built bodies; IEEE addition is commutative,
so the pairing — which the synthesis mirrors hop-for-hop — is the only
thing that matters for bit-parity) and ``new = recv`` for ``replace``.
Non-participating ranks mask the update and scatter their own values
back to DISTINCT pad slots (a duplicate index in one scatter would be
order-nondeterministic), so every rank runs the identical program.

``emit_allreduce_body`` verifies the schedule first — an unverifiable
schedule never lowers.
"""

from __future__ import annotations

from typing import Callable, List, Tuple, Union

import numpy as np

from hetu_galvatron_tpu.collectives.ir import Schedule, ScheduleError
from hetu_galvatron_tpu.collectives.verify import verify

Axis = Union[str, Tuple[str, ...]]


def _exchange_tables(sched: Schedule, step) -> Tuple:
    """(perm, send_tbl [n,K], recv_tbl [n,K], valid [n,K]) for one
    exchange step. Pad recv slots use chunk ids the rank does not
    otherwise touch this step, so the masked write-back never collides
    with a real write."""
    n, C = sched.n_ranks, sched.n_chunks
    K = max((len(x.chunks) for x in step.xfers), default=1)
    K = max(K, 1)
    if K > C:
        raise ScheduleError(
            f"step ({step.scope!r}): sends {K} chunks but the schedule "
            f"only has {C}")
    perm = [(x.src, x.dst) for x in step.xfers]
    send = np.zeros((n, K), np.int32)
    recv = np.zeros((n, K), np.int32)
    valid = np.zeros((n, K), bool)
    recv_set: List[set] = [set() for _ in range(n)]
    for x in step.xfers:
        send[x.src, :len(x.chunks)] = x.chunks
        recv[x.dst, :len(x.chunks)] = x.chunks
        valid[x.dst, :len(x.chunks)] = True
        recv_set[x.dst].update(x.chunks)
    for r in range(n):
        used = recv_set[r]
        free = iter(k for k in range(C) if k not in used)
        for j in range(K):
            if not valid[r, j]:
                recv[r, j] = next(free)
    return perm, send, recv, valid


def emit_allreduce_body(sched: Schedule, axis: Axis,
                        verify_first: bool = True) -> Callable:
    """A function of one flat per-device vector (length divisible by
    ``sched.n_chunks``) returning the schedule's result, to be called
    inside a full-manual shard_map whose ``axis`` group flattens to
    ``sched.n_ranks`` ranks. Works for any verified kind whose final
    state fills every rank (``all_reduce`` in the runtime path); other
    kinds lower too — the caller decides which slots are meaningful."""
    if verify_first:
        verify(sched)
    import jax
    import jax.numpy as jnp

    tables = []
    for step in sched.steps:
        if step.op == "exchange":
            perm, send, recv, valid = _exchange_tables(sched, step)
            tables.append((step.scope, step.combine, perm,
                           jnp.asarray(send), jnp.asarray(recv),
                           jnp.asarray(valid)))
        else:  # copy: per-rank (src, dst) slot moves
            n, C = sched.n_ranks, sched.n_chunks
            K = 1
            by_rank: List[List[Tuple[int, int]]] = [[] for _ in range(n)]
            for (r, a, b) in step.copies:
                by_rank[r].append((a, b))
                K = max(K, len(by_rank[r]))
            src = np.zeros((n, K), np.int32)
            dst = np.zeros((n, K), np.int32)
            valid = np.zeros((n, K), bool)
            for r, moves in enumerate(by_rank):
                used = {b for _, b in moves}
                free = iter(k for k in range(C) if k not in used)
                for j in range(K):
                    if j < len(moves):
                        src[r, j], dst[r, j] = moves[j]
                        valid[r, j] = True
                    else:
                        dst[r, j] = next(free)
                        src[r, j] = dst[r, j]
            tables.append((step.scope, "copy", None, jnp.asarray(src),
                           jnp.asarray(dst), jnp.asarray(valid)))

    C = sched.n_chunks

    def body(v):
        if v.shape[0] % C:
            raise ValueError(
                f"schedule {sched.name!r}: payload of {v.shape[0]} elems "
                f"does not split into {C} chunks (pad with "
                f"Schedule.padded_elems first)")
        r = jax.lax.axis_index(axis)
        buf = v.reshape(C, v.shape[0] // C)
        for (scope, combine, perm, send_t, recv_t, valid_t) in tables:
            with jax.named_scope(scope):
                sidx = jnp.take(send_t, r, axis=0)
                didx = jnp.take(recv_t, r, axis=0)
                ok = jnp.take(valid_t, r, axis=0)[:, None]
                cur = jnp.take(buf, didx, axis=0)
                if combine == "copy":
                    moved = jnp.take(buf, sidx, axis=0)
                else:
                    payload = jnp.take(buf, sidx, axis=0)
                    recv = jax.lax.ppermute(payload, axis, perm)
                    moved = (recv + cur) if combine == "add" else recv
                buf = buf.at[didx].set(jnp.where(ok, moved, cur))
        return buf.reshape(-1)

    return body
