"""Hierarchical dp/sdp gradient reduction: explicit two-level collectives.

Why: under GSPMD the dp gradient all-reduce is invisible — the partitioner
inserts ONE flat ring over the whole dp group at partition time, every
microbatch, with no way to steer the algorithm or the topology level
("Demystifying NCCL" / "Revisiting the Time Cost Model of AllReduce",
PAPERS.md: flat rings price the slowest link into every hop). On a
multi-slice mesh the dp group spans both the ICI domain and the DCN
seam (``runtime/mesh.py::dcn_factor_shape`` puts pp + outer dp on DCN),
so the right schedule is hierarchical: reduce-scatter INTRA-host at full
volume over the fast links, all-reduce ACROSS slices on the 1/k shard
(the only traffic that touches DCN), and all-gather the result back
intra-host. This module makes that schedule an EXPLICIT part of the
program so the static census can count it, the flow pass can weigh it,
and the cost model can price it per level.

Mechanics (two halves):

* **Per-lane gradients** — the flat path's partial sums exist only inside
  the partitioner, so the cross-dp sum is made explicit by computing
  per-dp-lane gradients: the batch's leading dim reshapes to
  ``[lanes, B/lanes, ...]`` with the lane axis sharded over the plan's dp
  mesh axes, and ``jax.vmap(grad_fn, in_axes=(None, 0))`` produces
  lane-stacked grads with ZERO cross-dp communication (each lane's
  devices already hold its samples; the per-device contraction is
  identical to the flat path's local work — only the cross-lane
  summation ORDER changes, a reduction reassociation within float
  tolerance). Gradient accumulation across microbatches stays lane-local,
  so a ``chunks``-microbatch step pays the dp reduction ONCE instead of
  the flat path's once-per-microbatch in-scan all-reduce.
* **The reduction** — ONE full-manual ``shard_map`` over
  :func:`~hetu_galvatron_tpu.runtime.mesh.hier_submesh` (the global mesh
  with the dp axes regrouped into the canonical
  :data:`~hetu_galvatron_tpu.runtime.mesh.HIER_SLICE_AXIS` /
  :data:`~hetu_galvatron_tpu.runtime.mesh.HIER_HOST_AXIS` sub-axes).
  Every grad leaf flattens and concatenates into ONE per-device payload
  vector (zero-padded to the intra-host degree), so the whole tree costs
  exactly three collective eqns per step — ``psum_scatter`` over the host
  axis at full volume, ``psum`` over the slice axis on the 1/intra shard,
  ``all_gather`` back — each under its ``jax.named_scope`` marker
  (:data:`HIER_DP_RS_SCOPE` etc.) so trace attribution and the census can
  bill them. ``telemetry.plan_collective_counts/bytes`` predict these
  counts and padded payload bytes EXACTLY from the same spec arithmetic
  (:func:`hier_payload_elems` / :func:`hier_bucket_layout`).

**Bucketed software pipelining** (``parallel.hier_bucket_mb > 0``): the
concatenated payload splits into fixed-capacity buckets and the
three-stage schedule is emitted in WAVEFRONT order across them — while
bucket *i* runs its cross-slice all-reduce on the DCN links, bucket
*i+1* runs its reduce-scatter and bucket *i−1* its all-gather on ICI.
The per-bucket chains are data-independent and the two link classes are
disjoint, so XLA's latency-hiding scheduler can overlap them: steady
state approaches ``max(Σ T_ici, T_dcn) + ramp`` instead of the
monolithic ``T_rs + T_ar + T_ag``. Each element still rides exactly the
same rs→ar→ag association as the monolithic path (a bucket is a
contiguous slice of the same payload), so results are bit-identical;
the program contains ``3 × buckets`` collectives, each under a
per-bucket-stage scope (``hier_dp_rs_b0`` …) that keeps trace
attribution, the census exemptions, and the plan-audit rows honest.
``hier_bucket_mb = 0`` (the default) is byte-for-byte today's single
bucket. :func:`hier_bucket_layout` is the ONE source for the per-bucket
(elems, padded) arithmetic — the runtime slicing and the census/flow
predictions both call it, so they cannot drift.

Eligibility lives in ``analysis/eligibility.py``
(``hier_dp_unsupported_reason``): uniform plans — cp/Ulysses layers ARE
eligible (the lane vmap covers the dp axes; each lane's leftover
cp/sequence-parallel partial sums stay an in-lane GSPMD reduction, and
the runtime swaps their shard_map attention kernels for the GSPMD core),
but not zigzag-cp (its pre-permuted data layout needs the ring kernel),
no dropout (lane mask streams would diverge from the flat path's), no
shard_map kernels under the lane vmap (tp_overlap rings / flash cannot
nest — and the pp engines keep their stage-stacked cp/ulysses kernels,
so pp>1 cp/sp plans stay flat), and the vocab tp axes must stay off the
dp lane axes.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from hetu_galvatron_tpu.runtime.mesh import (
    HIER_HOST_AXIS,
    HIER_SLICE_AXIS,
    LayerSharding,
    axes_size,
    hier_submesh,
)

# HLO-metadata markers (jax.named_scope) for the three hierarchical
# collectives — trace attribution (observability/trace_analysis.py) bills
# them to the dp component, and the sharding-flow reshard lint exempts the
# deliberate hier_dp_ag re-materialization. Bucketed schedules suffix a
# per-bucket stage id (hier_stage_scope: "hier_dp_rs_b3"); every consumer
# matches by SUBSTRING of the base scope, so the suffix only ADDS detail.
HIER_DP_RS_SCOPE = "hier_dp_rs"
HIER_DP_AR_SCOPE = "hier_dp_ar"
HIER_DP_AG_SCOPE = "hier_dp_ag"
HIER_DP_SCOPES = (HIER_DP_RS_SCOPE, HIER_DP_AR_SCOPE, HIER_DP_AG_SCOPE)

MB = 1024 * 1024


def hier_stage_scope(base: str, bucket: int, n_buckets: int) -> str:
    """named_scope for one bucket's stage: the bare base scope for the
    monolithic (single-bucket) schedule — byte-compatible with pre-bucket
    traces — else ``{base}_b{i}``. The base stays a prefix, so substring
    consumers (trace attribution ``_HIER_MARKERS``, the flow pass's
    ``hier_dp_ag`` gather exemption) see bucketed programs unchanged."""
    return base if n_buckets <= 1 else f"{base}_b{bucket}"


def hier_bucket_layout(local: int, intra: int,
                       bucket_mb: float) -> List[Tuple[int, int]]:
    """Per-bucket ``(elems, padded)`` split of the ``local`` per-device
    payload elements: contiguous f32 slices of at most ``bucket_mb``
    megabytes (rounded up to the intra-host degree so every full bucket
    scatters evenly), each independently zero-padded to a multiple of
    ``intra``. ``bucket_mb <= 0`` returns the single monolithic bucket —
    identical to :func:`hier_payload_elems`'s (local, padded) pair.

    This is THE bucket arithmetic: the runtime reducer slices its payload
    with it and ``telemetry.plan_collective_counts/bytes`` predict
    ``3 x len(layout)`` collectives with exactly these padded sizes —
    one function, two callers, no drift."""
    intra = max(intra, 1)
    pad = lambda n: -(-n // intra) * intra
    local = max(int(local), 0)
    if bucket_mb <= 0 or local == 0:
        return [(local, pad(local))]
    # capacity: bucket_mb of f32 elems, floored to a multiple of intra
    # (full buckets then scatter with zero padding), at least one tile
    cap = max((int(bucket_mb * MB) // 4) // intra * intra, intra)
    out: List[Tuple[int, int]] = []
    off = 0
    while off < local:
        n = min(cap, local - off)
        out.append((n, pad(n)))
        off += n
    return out


def _is_axes(x: Any) -> bool:
    return isinstance(x, tuple) and all(isinstance(s, str) for s in x)


def grad_reduce_specs(axes_tree: Any, per_layer: List[LayerSharding],
                      vocab: LayerSharding) -> Any:
    """PartitionSpec tree for the LANE-STACKED gradients' non-lane dims:
    the params' specs with ZeRO-3 dp-sharding overridden OFF (the lane
    axis owns the dp mesh axes; a leaf spec may not mention them twice).
    Mirrors ``parallel.spmd.param_specs``' row assignment — decoder layers
    use their own sharding, embed/prenorm/head the vocab sharding."""
    sp = lambda sh: (lambda la: sh.param_spec(la, zero3_override=False))
    tree = lambda axes, sh: jax.tree.map(sp(sh), axes, is_leaf=_is_axes)
    out = {
        "embed": tree(axes_tree["embed"], vocab),
        "layers": tuple(tree(a, sh)
                        for a, sh in zip(axes_tree["layers"], per_layer)),
        "prenorm": tree(axes_tree["prenorm"], vocab),
        "head": tree(axes_tree["head"], vocab),
    }
    if "enc_layers" in axes_tree:
        out["enc_layers"] = tuple(
            tree(a, per_layer[0]) for a in axes_tree["enc_layers"])
        out["enc_norm"] = tree(axes_tree["enc_norm"], vocab)
    return out


def hier_payload_elems(shapes: Sequence[Tuple[int, ...]],
                       specs: Sequence[P], mesh: Any,
                       intra: int) -> Tuple[int, int]:
    """(local, padded) per-device element counts of the concatenated
    reduction payload: each leaf contributes its GLOBAL size divided by
    the product of the mesh axes its spec shards it over, and the concat
    zero-pads up to the intra-host degree for the tiled scatter. This is
    the arithmetic ``plan_collective_bytes`` uses to predict the traced
    payload EXACTLY — one function, two callers, no drift. ``mesh`` only
    needs axis SIZES (``.shape``), so a shape-only stand-in works on a
    host with no devices (telemetry's plan prediction)."""
    local = 0
    for shape, spec in zip(shapes, specs):
        n = 1
        for d in shape:
            n *= int(d)
        div = 1
        for entry in spec:
            if entry is None:
                continue
            names = entry if isinstance(entry, tuple) else (entry,)
            div *= axes_size(mesh, names)
        local += n // div
    padded = -(-local // max(intra, 1)) * max(intra, 1)
    return local, padded


def _check_specs_off_lane_axes(specs: List[P],
                               dp_axes: Tuple[str, ...]) -> None:
    banned = set(dp_axes)
    for spec in specs:
        for entry in tuple(spec):
            names = (entry if isinstance(entry, tuple)
                     else (entry,) if entry else ())
            if banned & set(names):
                raise ValueError(
                    f"grad leaf spec {spec} shards a non-lane dim over the "
                    f"dp lane axes {dp_axes}; build the grad specs with "
                    "zero3_override=False (grad_reduce_specs)")


@dataclass
class HierDpReducer:
    """One plan's hierarchical dp gradient reducer, bound to a mesh.

    ``lanes`` is the plan's dp degree (the lane-vmap width);
    ``cross``/``intra`` the slice/host split of it. :meth:`reduce` takes a
    lane-stacked grad tree (leading ``[lanes]`` dim sharded over the dp
    axes, every other dim laid out per ``specs``) and returns the summed
    tree with the lane dim gone — three explicit collectives per bucket
    (one bucket at ``bucket_mb = 0``), software-pipelined across buckets.
    """

    mesh: Mesh
    dp_axes: Tuple[str, ...]
    cross: int
    intra: int
    # PartitionSpec tree matching the (unstacked) grad leaves; leaves that
    # carry extra stacked dims (the compiled engine's leading "pp") include
    # them in their own spec — the lane dim is prepended here
    specs: Any
    # the flat batch's [B, ...] spec (per_layer[0].batch_spec()); the lane
    # split re-pins dims past the lane one to it
    batch_spec: Optional[P] = None
    # bucketed software pipelining (module docstring): the payload splits
    # into ≤bucket_mb-MB buckets whose rs/ar/ag chains interleave so the
    # DCN stage of bucket i overlaps the ICI stages of its neighbours.
    # 0 = one monolithic bucket (byte-identical to the pre-bucket program)
    bucket_mb: float = 0.0
    # collective-compiler backend (collectives/): a schedule family name
    # ("ring" | "tree_hd" | "tree_bcast" | "torus2d" | "hier_rings")
    # synthesized for the dp group, statically verified, and emitted as
    # the reduction program in place of the hand-implemented
    # psum_scatter/psum/all_gather — or a hand-built reference body
    # ("ring_handbuilt" | "tree_handbuilt", collectives/reference.py)
    # for the bit-parity drills. None = the hand-implemented schedule.
    schedule: Optional[str] = None

    def __post_init__(self):
        self.lanes = axes_size(self.mesh, self.dp_axes)
        if self.lanes != self.cross * self.intra:
            raise ValueError(
                f"cross {self.cross} x intra {self.intra} != dp degree "
                f"{self.lanes}")
        self.hmesh = hier_submesh(self.mesh, self.dp_axes, self.cross)
        self._sched_body = None
        self._sched = None
        if self.schedule:
            from hetu_galvatron_tpu.analysis.eligibility import (
                dp_schedule_unsupported_reason,
            )

            reason = dp_schedule_unsupported_reason(
                self.schedule, self.lanes, self.cross, self.bucket_mb)
            if reason:
                raise ValueError(f"dp schedule unsupported: {reason}")
            axis = (HIER_SLICE_AXIS, HIER_HOST_AXIS)
            if self.schedule.endswith("_handbuilt"):
                from hetu_galvatron_tpu.collectives.reference import (
                    handbuilt_allreduce_body,
                )

                alg = self.schedule.split("_")[0]
                inner = handbuilt_allreduce_body(alg, self.lanes, axis)
                scope = f"dp_sched_handbuilt_{alg}"

                def body(v, _inner=inner, _scope=scope):
                    with jax.named_scope(_scope):
                        return _inner(v)

                self._sched_body = body
                self._sched_chunks = self.lanes
            else:
                from hetu_galvatron_tpu.collectives.emit import (
                    emit_allreduce_body,
                )
                from hetu_galvatron_tpu.collectives.synthesize import (
                    synthesize_dp_schedule,
                )
                from hetu_galvatron_tpu.collectives.verify import verify

                self._sched = verify(synthesize_dp_schedule(
                    self.schedule, self.lanes, self.cross))
                self._sched_body = emit_allreduce_body(
                    self._sched, axis, verify_first=False)
                self._sched_chunks = self._sched.n_chunks
        leaves, self._treedef = jax.tree_util.tree_flatten(
            self.specs, is_leaf=lambda x: isinstance(x, P))
        _check_specs_off_lane_axes(leaves, self.dp_axes)
        self._in_specs = tuple(
            P((HIER_SLICE_AXIS, HIER_HOST_AXIS), *s) for s in leaves)
        self._out_specs = tuple(leaves)
        self._leaf_specs = leaves
        self._lane_dim = tuple(self.dp_axes)
        self._fn = shard_map(self._body, self.hmesh,
                             in_specs=self._in_specs,
                             out_specs=self._out_specs, check_rep=False)

    # -- lane helpers -------------------------------------------------------

    def lane_batch(self, batch: Dict[str, jax.Array]) -> Dict[str, jax.Array]:
        """Reshape a batch tree's leading [B, ...] dim to [lanes, B/lanes,
        ...] with the lane axis pinned to the dp mesh axes (the flat
        batch's own dp sharding — the reshape moves no data)."""
        L = self.lanes
        batch_spec = (self.batch_spec if self.batch_spec is not None
                      else P(self._lane_dim))

        def split(x):
            if x.shape[0] % L:
                raise ValueError(
                    f"batch dim {x.shape[0]} not divisible by the dp lane "
                    f"count {L}")
            y = x.reshape((L, x.shape[0] // L) + x.shape[1:])
            rest = tuple(batch_spec)[1:]
            return jax.lax.with_sharding_constraint(
                y, NamedSharding(self.mesh,
                                 P(self._lane_dim, None, *rest)))

        return jax.tree.map(split, batch)

    def constrain_stacked(self, grads: Any) -> Any:
        """Pin a lane-stacked grad tree's layout (lane over dp axes, the
        rest per the leaf specs) — used on the scan carry so the
        accumulator never silently re-shards."""
        specs = jax.tree_util.tree_unflatten(
            self._treedef,
            [P(self._lane_dim, *s) for s in self._leaf_specs])
        return jax.tree.map(
            lambda g, s: jax.lax.with_sharding_constraint(
                g, NamedSharding(self.mesh, s)),
            grads, specs, is_leaf=lambda x: isinstance(x, P))

    # -- the reduction ------------------------------------------------------

    @staticmethod
    def _bucket_segments(sizes: Sequence[int],
                         layout: Sequence[Tuple[int, int]]
                         ) -> List[List[Tuple[int, int, int]]]:
        """Per-bucket ``(leaf index, lo, hi)`` segment lists covering the
        flattened leaves in order — the bucket boundaries fall wherever
        ``hier_bucket_layout`` put them, splitting a leaf mid-way when
        needed. Each element is copied exactly once INTO its bucket and
        once OUT (the same copy volume the monolithic concat/split pays),
        so bucketing adds no extra payload traffic."""
        segs: List[List[Tuple[int, int, int]]] = []
        li, lo = 0, 0
        for n, _padded in layout:
            bucket: List[Tuple[int, int, int]] = []
            need = n
            while need > 0:
                take = min(need, sizes[li] - lo)
                bucket.append((li, lo, lo + take))
                lo += take
                need -= take
                if lo == sizes[li]:
                    li += 1
                    lo = 0
            segs.append(bucket)
        return segs

    def _body(self, *blocks):
        """Local shard_map body: each block arrives ``[1, ...]`` (one lane
        per device along the regrouped dp sub-axes); flatten the leaves
        into per-bucket payload vectors (hier_bucket_layout — ONE bucket
        covering everything at bucket_mb = 0), run each bucket's
        three-level schedule with the stage emissions interleaved in
        wavefront order, and reassemble the leaves from the gathered
        buckets."""
        intra = self.intra
        flats = [b[0].reshape(-1).astype(jnp.float32) for b in blocks]
        sizes = [f.size for f in flats]
        if self._sched_body is not None:
            # collective-compiler path: ONE payload padded to a whole
            # number of schedule chunks, reduced by the emitted (or
            # hand-built reference) all-reduce program
            v = jnp.concatenate(flats) if len(flats) > 1 else flats[0]
            local = v.shape[0]
            C = self._sched_chunks
            padded = -(-local // C) * C
            if padded != local:
                v = jnp.pad(v, (0, padded - local))
            g = self._sched_body(v)[:local]
            outs = []
            off = 0
            for b, n in zip(blocks, sizes):
                outs.append(g[off:off + n].reshape(b.shape[1:])
                            .astype(b.dtype))
                off += n
            return tuple(outs)
        layout = hier_bucket_layout(sum(sizes), intra, self.bucket_mb)
        segs = self._bucket_segments(sizes, layout)
        B = len(layout)
        bufs = []
        for bucket, (n, padded) in zip(segs, layout):
            parts = [flats[li][lo:hi] if (lo, hi) != (0, sizes[li])
                     else flats[li] for li, lo, hi in bucket]
            v = jnp.concatenate(parts) if len(parts) > 1 else parts[0]
            if padded != n:
                v = jnp.pad(v, (0, padded - n))
            bufs.append(v)
        # wavefront emission: at step t, bucket t enters rs-intra (ICI)
        # while bucket t-1 runs ar-cross (DCN) and bucket t-2 ag-intra
        # (ICI). The chains share no data, so the emission order is the
        # overlap HINT the latency-hiding scheduler needs — with B = 1
        # this degenerates to exactly the monolithic three-collective
        # program (same scopes, same payload, same bytes).
        rs_out: List[Any] = [None] * B
        ar_out: List[Any] = [None] * B
        ag_out: List[Any] = [None] * B
        for t in range(B + 2):
            if t < B:
                with jax.named_scope(
                        hier_stage_scope(HIER_DP_RS_SCOPE, t, B)):
                    rs_out[t] = jax.lax.psum_scatter(
                        bufs[t], HIER_HOST_AXIS, scatter_dimension=0,
                        tiled=True)
            j = t - 1
            if 0 <= j < B:
                with jax.named_scope(
                        hier_stage_scope(HIER_DP_AR_SCOPE, j, B)):
                    ar_out[j] = jax.lax.psum(rs_out[j], HIER_SLICE_AXIS)
            k = t - 2
            if 0 <= k < B:
                with jax.named_scope(
                        hier_stage_scope(HIER_DP_AG_SCOPE, k, B)):
                    ag_out[k] = jax.lax.all_gather(
                        ar_out[k], HIER_HOST_AXIS, tiled=True)
        # reassemble each leaf from its (in-order) bucket segments
        pieces: List[List[Any]] = [[] for _ in flats]
        for bucket, (n, padded), g in zip(segs, layout, ag_out):
            off = 0
            for li, lo, hi in bucket:
                pieces[li].append(g[off:off + (hi - lo)])
                off += hi - lo
        outs = []
        for b, n, ps in zip(blocks, sizes, pieces):
            leaf = jnp.concatenate(ps) if len(ps) > 1 else ps[0]
            outs.append(leaf.reshape(b.shape[1:]).astype(b.dtype))
        return tuple(outs)

    def reduce(self, stacked: Any) -> Any:
        """Lane-stacked grads ``[lanes, ...]`` -> summed grads (lane dim
        dropped), via the one three-collective program."""
        leaves = jax.tree_util.tree_leaves(stacked)
        if len(leaves) != len(self._leaf_specs):
            raise ValueError(
                f"grad tree has {len(leaves)} leaves, reducer was built "
                f"for {len(self._leaf_specs)}")
        outs = self._fn(*leaves)
        return jax.tree_util.tree_unflatten(self._treedef, list(outs))

    def payload_elems(self, stacked_or_shapes: Any) -> Tuple[int, int]:
        """(local, padded) payload element counts — the traced-byte
        prediction's anchor. Accepts either a LANE-STACKED grad tree
        (leaf lane dims stripped) or a flat list of UNSTACKED global leaf
        shape tuples in spec order."""
        if isinstance(stacked_or_shapes, (list, tuple)) and all(
                isinstance(s, tuple) for s in stacked_or_shapes):
            shapes = [tuple(s) for s in stacked_or_shapes]
        else:
            shapes = [tuple(l.shape[1:]) for l in
                      jax.tree_util.tree_leaves(stacked_or_shapes)]
        return hier_payload_elems(shapes, self._leaf_specs, self.hmesh,
                                  self.intra)

    def bucket_layout(self, stacked_or_shapes: Any) -> List[Tuple[int, int]]:
        """Per-bucket (elems, padded) split of this reducer's payload —
        the exact slices :meth:`reduce` emits (``hier_bucket_layout`` over
        :meth:`payload_elems`'s local count). One entry at
        ``bucket_mb = 0``."""
        local, _ = self.payload_elems(stacked_or_shapes)
        return hier_bucket_layout(local, self.intra, self.bucket_mb)


def make_hier_reducer(
    mesh: Mesh,
    per_layer: List[LayerSharding],
    vocab: LayerSharding,
    axes_tree: Any,
    *,
    dcn_slices: int = 1,
    cross: Optional[int] = None,
    specs: Any = None,
    bucket_mb: float = 0.0,
    schedule: Optional[str] = None,
) -> HierDpReducer:
    """Build the reducer for a lowered plan: dp lane axes from the (uniform)
    first decoder layer, the slice/host split from ``dcn_slices`` (pp-first
    absorption, ``mesh.hier_cross_degree``) unless ``cross`` pins it, grad
    specs from :func:`grad_reduce_specs` unless given, and the bucketed
    pipelining granularity from ``bucket_mb`` (``parallel.hier_bucket_mb``;
    0 = one monolithic bucket)."""
    from hetu_galvatron_tpu.runtime.mesh import hier_cross_degree

    sh = per_layer[0]
    dp_axes = sh.dp_axes
    dp_deg = axes_size(mesh, dp_axes)
    if cross is None:
        cross = hier_cross_degree(mesh.shape.get("pp", 1), dp_deg,
                                  dcn_slices)
    if specs is None:
        specs = grad_reduce_specs(axes_tree, per_layer, vocab)
    return HierDpReducer(mesh=mesh, dp_axes=dp_axes, cross=cross,
                         intra=dp_deg // cross, specs=specs,
                         batch_spec=sh.batch_spec(), bucket_mb=bucket_mb,
                         schedule=schedule)


# NOTE: per-lane grad computation is NOT wrapped here on purpose — every
# caller (trainer / both pipeline engines) must build its own
# ``jax.vmap(grad_fn, in_axes=(None, 0), spmd_axis_name=dp_axes)`` with
# lane-aware (dp-free) interior shardings; a generic helper without the
# axis pinning would silently reintroduce the per-layer lane reshard this
# module's docstring warns about.
