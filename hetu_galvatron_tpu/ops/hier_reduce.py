"""Hierarchical dp/sdp gradient reduction: explicit two-level collectives.

Why: under GSPMD the dp gradient all-reduce is invisible — the partitioner
inserts ONE flat ring over the whole dp group at partition time, every
microbatch, with no way to steer the algorithm or the topology level
("Demystifying NCCL" / "Revisiting the Time Cost Model of AllReduce",
PAPERS.md: flat rings price the slowest link into every hop). On a
multi-slice mesh the dp group spans both the ICI domain and the DCN
seam (``runtime/mesh.py::dcn_factor_shape`` puts pp + outer dp on DCN),
so the right schedule is hierarchical: reduce-scatter INTRA-host at full
volume over the fast links, all-reduce ACROSS slices on the 1/k shard
(the only traffic that touches DCN), and all-gather the result back
intra-host. This module makes that schedule an EXPLICIT part of the
program so the static census can count it, the flow pass can weigh it,
and the cost model can price it per level.

Mechanics (two halves):

* **Per-lane gradients** — the flat path's partial sums exist only inside
  the partitioner, so the cross-dp sum is made explicit by computing
  per-dp-lane gradients: the batch's leading dim reshapes to
  ``[lanes, B/lanes, ...]`` with the lane axis sharded over the plan's dp
  mesh axes, and ``jax.vmap(grad_fn, in_axes=(None, 0))`` produces
  lane-stacked grads with ZERO cross-dp communication (each lane's
  devices already hold its samples; the per-device contraction is
  identical to the flat path's local work — only the cross-lane
  summation ORDER changes, a reduction reassociation within float
  tolerance). Gradient accumulation across microbatches stays lane-local,
  so a ``chunks``-microbatch step pays the dp reduction ONCE instead of
  the flat path's once-per-microbatch in-scan all-reduce.
* **The reduction** — ONE full-manual ``shard_map`` over
  :func:`~hetu_galvatron_tpu.runtime.mesh.hier_submesh` (the global mesh
  with the dp axes regrouped into the canonical
  :data:`~hetu_galvatron_tpu.runtime.mesh.HIER_SLICE_AXIS` /
  :data:`~hetu_galvatron_tpu.runtime.mesh.HIER_HOST_AXIS` sub-axes).
  Every grad leaf flattens and concatenates into ONE per-device payload
  vector (zero-padded to the intra-host degree), so the whole tree costs
  exactly three collective eqns per step — ``psum_scatter`` over the host
  axis at full volume, ``psum`` over the slice axis on the 1/intra shard,
  ``all_gather`` back — each under its ``jax.named_scope`` marker
  (:data:`HIER_DP_RS_SCOPE` etc.) so trace attribution and the census can
  bill them. ``telemetry.plan_collective_counts/bytes`` predict these
  counts and padded payload bytes EXACTLY from the same spec arithmetic
  (:func:`hier_payload_elems`).

Eligibility lives in ``analysis/eligibility.py``
(``hier_dp_unsupported_reason``): uniform Megatron-TP plans only — no
cp/Ulysses (their grads are partial over more than dp), no dropout (lane
mask streams would diverge from the flat path's), no shard_map kernels
under the lane vmap (tp_overlap rings / flash / ring-cp cannot nest), and
the vocab tp axes must stay off the dp lane axes.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from hetu_galvatron_tpu.runtime.mesh import (
    HIER_HOST_AXIS,
    HIER_SLICE_AXIS,
    LayerSharding,
    axes_size,
    hier_submesh,
)

# HLO-metadata markers (jax.named_scope) for the three hierarchical
# collectives — trace attribution (observability/trace_analysis.py) bills
# them to the dp component, and the sharding-flow reshard lint exempts the
# deliberate hier_dp_ag re-materialization
HIER_DP_RS_SCOPE = "hier_dp_rs"
HIER_DP_AR_SCOPE = "hier_dp_ar"
HIER_DP_AG_SCOPE = "hier_dp_ag"
HIER_DP_SCOPES = (HIER_DP_RS_SCOPE, HIER_DP_AR_SCOPE, HIER_DP_AG_SCOPE)


def _is_axes(x: Any) -> bool:
    return isinstance(x, tuple) and all(isinstance(s, str) for s in x)


def grad_reduce_specs(axes_tree: Any, per_layer: List[LayerSharding],
                      vocab: LayerSharding) -> Any:
    """PartitionSpec tree for the LANE-STACKED gradients' non-lane dims:
    the params' specs with ZeRO-3 dp-sharding overridden OFF (the lane
    axis owns the dp mesh axes; a leaf spec may not mention them twice).
    Mirrors ``parallel.spmd.param_specs``' row assignment — decoder layers
    use their own sharding, embed/prenorm/head the vocab sharding."""
    sp = lambda sh: (lambda la: sh.param_spec(la, zero3_override=False))
    tree = lambda axes, sh: jax.tree.map(sp(sh), axes, is_leaf=_is_axes)
    out = {
        "embed": tree(axes_tree["embed"], vocab),
        "layers": tuple(tree(a, sh)
                        for a, sh in zip(axes_tree["layers"], per_layer)),
        "prenorm": tree(axes_tree["prenorm"], vocab),
        "head": tree(axes_tree["head"], vocab),
    }
    if "enc_layers" in axes_tree:
        out["enc_layers"] = tuple(
            tree(a, per_layer[0]) for a in axes_tree["enc_layers"])
        out["enc_norm"] = tree(axes_tree["enc_norm"], vocab)
    return out


def hier_payload_elems(shapes: Sequence[Tuple[int, ...]],
                       specs: Sequence[P], mesh: Any,
                       intra: int) -> Tuple[int, int]:
    """(local, padded) per-device element counts of the concatenated
    reduction payload: each leaf contributes its GLOBAL size divided by
    the product of the mesh axes its spec shards it over, and the concat
    zero-pads up to the intra-host degree for the tiled scatter. This is
    the arithmetic ``plan_collective_bytes`` uses to predict the traced
    payload EXACTLY — one function, two callers, no drift. ``mesh`` only
    needs axis SIZES (``.shape``), so a shape-only stand-in works on a
    host with no devices (telemetry's plan prediction)."""
    local = 0
    for shape, spec in zip(shapes, specs):
        n = 1
        for d in shape:
            n *= int(d)
        div = 1
        for entry in spec:
            if entry is None:
                continue
            names = entry if isinstance(entry, tuple) else (entry,)
            div *= axes_size(mesh, names)
        local += n // div
    padded = -(-local // max(intra, 1)) * max(intra, 1)
    return local, padded


def _check_specs_off_lane_axes(specs: List[P],
                               dp_axes: Tuple[str, ...]) -> None:
    banned = set(dp_axes)
    for spec in specs:
        for entry in tuple(spec):
            names = (entry if isinstance(entry, tuple)
                     else (entry,) if entry else ())
            if banned & set(names):
                raise ValueError(
                    f"grad leaf spec {spec} shards a non-lane dim over the "
                    f"dp lane axes {dp_axes}; build the grad specs with "
                    "zero3_override=False (grad_reduce_specs)")


@dataclass
class HierDpReducer:
    """One plan's hierarchical dp gradient reducer, bound to a mesh.

    ``lanes`` is the plan's dp degree (the lane-vmap width);
    ``cross``/``intra`` the slice/host split of it. :meth:`reduce` takes a
    lane-stacked grad tree (leading ``[lanes]`` dim sharded over the dp
    axes, every other dim laid out per ``specs``) and returns the summed
    tree with the lane dim gone — three explicit collectives total.
    """

    mesh: Mesh
    dp_axes: Tuple[str, ...]
    cross: int
    intra: int
    # PartitionSpec tree matching the (unstacked) grad leaves; leaves that
    # carry extra stacked dims (the compiled engine's leading "pp") include
    # them in their own spec — the lane dim is prepended here
    specs: Any
    # the flat batch's [B, ...] spec (per_layer[0].batch_spec()); the lane
    # split re-pins dims past the lane one to it
    batch_spec: Optional[P] = None

    def __post_init__(self):
        self.lanes = axes_size(self.mesh, self.dp_axes)
        if self.lanes != self.cross * self.intra:
            raise ValueError(
                f"cross {self.cross} x intra {self.intra} != dp degree "
                f"{self.lanes}")
        self.hmesh = hier_submesh(self.mesh, self.dp_axes, self.cross)
        leaves, self._treedef = jax.tree_util.tree_flatten(
            self.specs, is_leaf=lambda x: isinstance(x, P))
        _check_specs_off_lane_axes(leaves, self.dp_axes)
        self._in_specs = tuple(
            P((HIER_SLICE_AXIS, HIER_HOST_AXIS), *s) for s in leaves)
        self._out_specs = tuple(leaves)
        self._leaf_specs = leaves
        self._lane_dim = tuple(self.dp_axes)
        self._fn = shard_map(self._body, self.hmesh,
                             in_specs=self._in_specs,
                             out_specs=self._out_specs, check_rep=False)

    # -- lane helpers -------------------------------------------------------

    def lane_batch(self, batch: Dict[str, jax.Array]) -> Dict[str, jax.Array]:
        """Reshape a batch tree's leading [B, ...] dim to [lanes, B/lanes,
        ...] with the lane axis pinned to the dp mesh axes (the flat
        batch's own dp sharding — the reshape moves no data)."""
        L = self.lanes
        batch_spec = (self.batch_spec if self.batch_spec is not None
                      else P(self._lane_dim))

        def split(x):
            if x.shape[0] % L:
                raise ValueError(
                    f"batch dim {x.shape[0]} not divisible by the dp lane "
                    f"count {L}")
            y = x.reshape((L, x.shape[0] // L) + x.shape[1:])
            rest = tuple(batch_spec)[1:]
            return jax.lax.with_sharding_constraint(
                y, NamedSharding(self.mesh,
                                 P(self._lane_dim, None, *rest)))

        return jax.tree.map(split, batch)

    def constrain_stacked(self, grads: Any) -> Any:
        """Pin a lane-stacked grad tree's layout (lane over dp axes, the
        rest per the leaf specs) — used on the scan carry so the
        accumulator never silently re-shards."""
        specs = jax.tree_util.tree_unflatten(
            self._treedef,
            [P(self._lane_dim, *s) for s in self._leaf_specs])
        return jax.tree.map(
            lambda g, s: jax.lax.with_sharding_constraint(
                g, NamedSharding(self.mesh, s)),
            grads, specs, is_leaf=lambda x: isinstance(x, P))

    # -- the reduction ------------------------------------------------------

    def _body(self, *blocks):
        """Local shard_map body: each block arrives ``[1, ...]`` (one lane
        per device along the regrouped dp sub-axes); flatten-concat-pad to
        one payload vector, run the three-level schedule, split back."""
        intra = self.intra
        flats = [b[0].reshape(-1).astype(jnp.float32) for b in blocks]
        sizes = [f.size for f in flats]
        v = jnp.concatenate(flats) if len(flats) > 1 else flats[0]
        pad = (-v.size) % intra
        if pad:
            v = jnp.pad(v, (0, pad))
        with jax.named_scope(HIER_DP_RS_SCOPE):
            s = jax.lax.psum_scatter(v, HIER_HOST_AXIS,
                                     scatter_dimension=0, tiled=True)
        with jax.named_scope(HIER_DP_AR_SCOPE):
            s = jax.lax.psum(s, HIER_SLICE_AXIS)
        with jax.named_scope(HIER_DP_AG_SCOPE):
            full = jax.lax.all_gather(s, HIER_HOST_AXIS, tiled=True)
        if pad:
            full = full[:sum(sizes)]
        outs, off = [], 0
        for b, n in zip(blocks, sizes):
            outs.append(full[off:off + n].reshape(b.shape[1:])
                        .astype(b.dtype))
            off += n
        return tuple(outs)

    def reduce(self, stacked: Any) -> Any:
        """Lane-stacked grads ``[lanes, ...]`` -> summed grads (lane dim
        dropped), via the one three-collective program."""
        leaves = jax.tree_util.tree_leaves(stacked)
        if len(leaves) != len(self._leaf_specs):
            raise ValueError(
                f"grad tree has {len(leaves)} leaves, reducer was built "
                f"for {len(self._leaf_specs)}")
        outs = self._fn(*leaves)
        return jax.tree_util.tree_unflatten(self._treedef, list(outs))

    def payload_elems(self, stacked_or_shapes: Any) -> Tuple[int, int]:
        """(local, padded) payload element counts — the traced-byte
        prediction's anchor. Accepts either a LANE-STACKED grad tree
        (leaf lane dims stripped) or a flat list of UNSTACKED global leaf
        shape tuples in spec order."""
        if isinstance(stacked_or_shapes, (list, tuple)) and all(
                isinstance(s, tuple) for s in stacked_or_shapes):
            shapes = [tuple(s) for s in stacked_or_shapes]
        else:
            shapes = [tuple(l.shape[1:]) for l in
                      jax.tree_util.tree_leaves(stacked_or_shapes)]
        return hier_payload_elems(shapes, self._leaf_specs, self.hmesh,
                                  self.intra)


def make_hier_reducer(
    mesh: Mesh,
    per_layer: List[LayerSharding],
    vocab: LayerSharding,
    axes_tree: Any,
    *,
    dcn_slices: int = 1,
    cross: Optional[int] = None,
    specs: Any = None,
) -> HierDpReducer:
    """Build the reducer for a lowered plan: dp lane axes from the (uniform)
    first decoder layer, the slice/host split from ``dcn_slices`` (pp-first
    absorption, ``mesh.hier_cross_degree``) unless ``cross`` pins it, and
    grad specs from :func:`grad_reduce_specs` unless given."""
    from hetu_galvatron_tpu.runtime.mesh import hier_cross_degree

    sh = per_layer[0]
    dp_axes = sh.dp_axes
    dp_deg = axes_size(mesh, dp_axes)
    if cross is None:
        cross = hier_cross_degree(mesh.shape.get("pp", 1), dp_deg,
                                  dcn_slices)
    if specs is None:
        specs = grad_reduce_specs(axes_tree, per_layer, vocab)
    return HierDpReducer(mesh=mesh, dp_axes=dp_axes, cross=cross,
                         intra=dp_deg // cross, specs=specs,
                         batch_spec=sh.batch_spec())


# NOTE: per-lane grad computation is NOT wrapped here on purpose — every
# caller (trainer / both pipeline engines) must build its own
# ``jax.vmap(grad_fn, in_axes=(None, 0), spmd_axis_name=dp_axes)`` with
# lane-aware (dp-free) interior shardings; a generic helper without the
# axis pinning would silently reintroduce the per-layer lane reshard this
# module's docstring warns about.
