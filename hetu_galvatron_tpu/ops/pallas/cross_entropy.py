"""Pallas fused cross-entropy for TPU: online logsumexp + label gather.

TPU-native replacement for the reference's Triton vocab-parallel CE
(tensor_parallel/triton_cross_entropy.py:219-270; SURVEY §2 native-code
checklist item 3). The [T, V] logits never round-trip HBM in f32: the
forward sweeps vocab tiles once (running max / normalizer / gold
accumulator in VMEM, f32 compute from bf16 tiles), and the backward
recomputes softmax per tile from the saved logsumexp to emit dlogits in
the input dtype. XLA's lowering materializes the f32 cast and reads the
logits separately for logsumexp and gather; the fused kernel reads each
tile exactly once per direction.

The z-loss term (nll += z * lse^2) folds into the same saved-lse backward:
dlogits = softmax * (g * (1 + 2z*lse)) - onehot * g.

Row reductions (masking, mean) stay in XLA — they are O(T) and fuse fine.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# pin-compat: the CompilerParams dataclass was named TPUCompilerParams on
# older jax releases (this toolchain's pin); same fields either way
_CompilerParams = getattr(pltpu, "CompilerParams",
                          getattr(pltpu, "TPUCompilerParams", None))

NEG_INF = float(jnp.finfo(jnp.float32).min)


def fit_vocab_block(v: int, candidates=(2048, 1024, 512, 256, 128)) -> int:
    """Largest lane-aligned tile that divides the vocab; 0 if none (caller
    falls back to the XLA path). GPT-2's padded 50304 fits 128; LLaMA's
    32000 fits 256."""
    for c in candidates:
        if v % c == 0:
            return c
    return 0


def _ce_fwd_kernel(x_ref, lab_ref, lse_ref, gold_ref, m_ref, l_ref, g_ref,
                   *, block_v: int, num_v: int):
    vi = pl.program_id(1)

    @pl.when(vi == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        g_ref[...] = jnp.zeros_like(g_ref)

    x = x_ref[...].astype(jnp.float32)  # (bt, bv)
    bt, bv = x.shape
    lab = lab_ref[...]  # (bt, 1) int32
    vpos = vi * block_v + jax.lax.broadcasted_iota(jnp.int32, (bt, bv), 1)
    m = m_ref[...]
    new_m = jnp.maximum(m, jnp.max(x, axis=1))
    corr = jnp.exp(jnp.where(m == NEG_INF, NEG_INF, m - new_m))
    l_ref[...] = l_ref[...] * corr + jnp.sum(jnp.exp(x - new_m[:, None]),
                                             axis=1)
    m_ref[...] = new_m
    # the gold logit lands in exactly one vocab tile per row
    g_ref[...] += jnp.sum(jnp.where(vpos == lab, x, 0.0), axis=1)

    @pl.when(vi == num_v - 1)
    def _fin():
        lse_ref[...] = (m_ref[...]
                        + jnp.log(jnp.maximum(l_ref[...], 1e-30)))[:, None]
        gold_ref[...] = g_ref[...][:, None]


def _ce_bwd_kernel(x_ref, lab_ref, lse_ref, a_ref, b_ref, dx_ref,
                   *, block_v: int):
    """dlogits = softmax * a - onehot * b, per (row, vocab-tile)."""
    vi = pl.program_id(1)
    x = x_ref[...].astype(jnp.float32)
    bt, bv = x.shape
    lab = lab_ref[...]
    vpos = vi * block_v + jax.lax.broadcasted_iota(jnp.int32, (bt, bv), 1)
    p = jnp.exp(x - lse_ref[...])
    dx = p * a_ref[...] - jnp.where(vpos == lab, b_ref[...], 0.0)
    dx_ref[...] = dx.astype(dx_ref.dtype)


@functools.partial(jax.jit, static_argnames=("block_t", "block_v",
                                             "interpret"))
def _ce_fwd_call(logits, labels2d, *, block_t, block_v, interpret):
    T, V = logits.shape
    num_t, num_v = T // block_t, V // block_v
    return pl.pallas_call(
        functools.partial(_ce_fwd_kernel, block_v=block_v, num_v=num_v),
        grid=(num_t, num_v),
        in_specs=[
            pl.BlockSpec((block_t, block_v), lambda t, v: (t, v)),
            pl.BlockSpec((block_t, 1), lambda t, v: (t, 0)),
        ],
        out_specs=[
            pl.BlockSpec((block_t, 1), lambda t, v: (t, 0)),
            pl.BlockSpec((block_t, 1), lambda t, v: (t, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((T, 1), jnp.float32),  # lse
            jax.ShapeDtypeStruct((T, 1), jnp.float32),  # gold logit
        ],
        scratch_shapes=[
            pltpu.VMEM((block_t,), jnp.float32),
            pltpu.VMEM((block_t,), jnp.float32),
            pltpu.VMEM((block_t,), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(logits, labels2d)


@functools.partial(jax.jit, static_argnames=("block_t", "block_v",
                                             "interpret"))
def _ce_bwd_call(logits, labels2d, lse, a, b, *, block_t, block_v,
                 interpret):
    T, V = logits.shape
    return pl.pallas_call(
        functools.partial(_ce_bwd_kernel, block_v=block_v),
        grid=(T // block_t, V // block_v),
        in_specs=[
            pl.BlockSpec((block_t, block_v), lambda t, v: (t, v)),
            pl.BlockSpec((block_t, 1), lambda t, v: (t, 0)),
            pl.BlockSpec((block_t, 1), lambda t, v: (t, 0)),
            pl.BlockSpec((block_t, 1), lambda t, v: (t, 0)),
            pl.BlockSpec((block_t, 1), lambda t, v: (t, 0)),
        ],
        out_specs=pl.BlockSpec((block_t, block_v), lambda t, v: (t, v)),
        out_shape=jax.ShapeDtypeStruct((T, V), logits.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel")),
        interpret=interpret,
    )(logits, labels2d, lse, a, b)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _ce_lse_gold(logits, labels2d, block_t, block_v, interpret):
    """Differentiable (lse[T,1], gold[T,1]) via the fused kernels. The nll
    (and any z-loss / cross-shard combine) is plain JAX on top, so its
    gradient flows through this VJP: d logits = softmax * d_lse
    + onehot * d_gold, which the backward kernel emits per vocab tile."""
    return _ce_fwd_call(logits, labels2d, block_t=block_t,
                        block_v=block_v, interpret=interpret)


def _ce_lse_gold_fwd(logits, labels2d, block_t, block_v, interpret):
    lse, gold = _ce_fwd_call(logits, labels2d, block_t=block_t,
                             block_v=block_v, interpret=interpret)
    return (lse, gold), (logits, labels2d, lse)


def _ce_lse_gold_bwd(block_t, block_v, interpret, res, g):
    logits, labels2d, lse = res
    d_lse, d_gold = g
    dx = _ce_bwd_call(logits, labels2d, lse,
                      d_lse.astype(jnp.float32),
                      -d_gold.astype(jnp.float32),
                      block_t=block_t, block_v=block_v, interpret=interpret)
    return dx, np.zeros(labels2d.shape, dtype=jax.dtypes.float0)


_ce_lse_gold.defvjp(_ce_lse_gold_fwd, _ce_lse_gold_bwd)


def _fit_blocks(T: int, V: int, block_t: int):
    bv = fit_vocab_block(V)
    bt = block_t
    while bt > 8 and T % bt:
        bt //= 2
    if not bv or T % bt:
        return None
    return bt, bv


def fused_ce_nll(logits: jax.Array, labels: jax.Array, *,
                 z_loss: float = 0.0, interpret: bool = False,
                 block_t: int = 256) -> jax.Array | None:
    """Per-token NLL via the fused kernel, or None when the shape cannot
    tile (caller uses the XLA path). logits [..., V] any leading dims,
    labels matching leading dims."""
    V = logits.shape[-1]
    lead = logits.shape[:-1]
    T = int(np.prod(lead)) if lead else 1
    fit = _fit_blocks(T, V, block_t)
    if fit is None:
        return None
    bt, bv = fit
    # Mosaic only exists on TPU; anywhere else (CPU tests, smoke runs) the
    # kernel runs in interpret mode so the flag is safe on any backend
    interpret = interpret or jax.default_backend() != "tpu"
    lse, gold = _ce_lse_gold(logits.reshape(T, V),
                             labels.reshape(T, 1).astype(jnp.int32),
                             bt, bv, interpret)
    nll = lse[:, 0] - gold[:, 0]
    if z_loss:
        nll = nll + z_loss * jnp.square(lse[:, 0])
    return nll.reshape(lead)


def make_vocab_parallel_ce(mesh, vocab_sharding, *, z_loss: float = 0.0,
                           interpret: bool = False, block_t: int = 256):
    # NOTE: the returned nll_fn accepts a per-call z_loss override so
    # cross_entropy_loss's z_loss parameter behaves identically whether
    # `fused` is True (kernel direct) or this callable (see modules.py).
    """Distributed fused CE: per-token NLL over logits sharded by the
    embedding/LM-head strategy — the TPU counterpart of the reference's
    vocab-parallel Triton CE (triton_cross_entropy.py:219-270), which
    reduces per-shard (max, sumexp, gold) across the TP group.

    Under shard_map each shard runs the fused kernel on its local
    [B_l, S_l, V_l] logits; when the vocab dim is sharded (vtp without
    vsp), local gold/lse combine with a pmax/psum logsumexp merge. With
    vsp (ulysses-style: sequence sharded, head replicated) no collective
    is needed. Returns ``nll_fn(logits, labels) -> nll`` or None when the
    local shapes cannot tile.
    """
    from jax.sharding import PartitionSpec as P

    sh = vocab_sharding
    seq_axes = tuple(sh.cp_axes) + (tuple(sh.tp_axes) if sh.ulysses else ())
    vocab_axes = () if sh.ulysses else tuple(sh.tp_axes)
    n_vocab_shards = int(np.prod([mesh.shape[a] for a in vocab_axes])) \
        if vocab_axes else 1
    n_seq = int(np.prod([mesh.shape[a] for a in seq_axes])) if seq_axes else 1
    n_dp = int(np.prod([mesh.shape[a] for a in sh.dp_axes])) \
        if sh.dp_axes else 1
    logits_spec = P(sh.dp_axes or None, seq_axes or None, vocab_axes or None)
    labels_spec = P(sh.dp_axes or None, seq_axes or None)

    def nll_fn(logits, labels, z_loss=z_loss):
        B, S, V = logits.shape
        if V % n_vocab_shards or S % n_seq or B % n_dp:
            return None
        fit = _fit_blocks((B // n_dp) * (S // n_seq), V // n_vocab_shards,
                          block_t)
        if fit is None:
            return None
        bt, bv = fit
        interp = interpret or jax.default_backend() != "tpu"

        def local(lg, lb):
            Bl, Sl, Vl = lg.shape
            offset = jnp.int32(0)
            for ax in vocab_axes:  # major-to-minor, matching P's layout
                offset = offset * mesh.shape[ax] + jax.lax.axis_index(ax)
            lab = lb.reshape(-1, 1).astype(jnp.int32) - offset * Vl
            lse, gold = _ce_lse_gold(lg.reshape(-1, Vl), lab, bt, bv, interp)
            if vocab_axes:
                # logsumexp merge across vocab shards; m is a numerical
                # anchor only (lse is m-independent) so it takes no
                # gradient — and pmax has no JVP rule, so stop_gradient
                # must come BEFORE it (pmax then only ever sees constants)
                m = jax.lax.pmax(jax.lax.stop_gradient(lse), vocab_axes)
                lse = m + jnp.log(jax.lax.psum(jnp.exp(lse - m), vocab_axes))
                gold = jax.lax.psum(gold, vocab_axes)
            nll = lse[:, 0] - gold[:, 0]
            if z_loss:
                nll = nll + z_loss * jnp.square(lse[:, 0])
            return nll.reshape(Bl, Sl)

        from jax.experimental.shard_map import shard_map

        return shard_map(local, mesh=mesh,
                             in_specs=(logits_spec, labels_spec),
                             out_specs=labels_spec,
                             check_rep=False)(logits, labels)

    return nll_fn
