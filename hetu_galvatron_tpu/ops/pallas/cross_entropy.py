"""Pallas fused cross-entropy for TPU: online logsumexp + label gather.

TPU-native replacement for the reference's Triton vocab-parallel CE
(tensor_parallel/triton_cross_entropy.py:219-270; SURVEY §2 native-code
checklist item 3). The [T, V] logits never round-trip HBM in f32: the
forward sweeps vocab tiles once (running max / normalizer / gold
accumulator in VMEM, f32 compute from bf16 tiles), and the backward
recomputes softmax per tile from the saved logsumexp to emit dlogits in
the input dtype. XLA's lowering materializes the f32 cast and reads the
logits separately for logsumexp and gather; the fused kernel reads each
tile exactly once per direction.

The z-loss term (nll += z * lse^2) folds into the same saved-lse backward:
dlogits = softmax * (g * (1 + 2z*lse)) - onehot * g.

Row reductions (masking, mean) stay in XLA — they are O(T) and fuse fine.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = float(jnp.finfo(jnp.float32).min)


def fit_vocab_block(v: int, candidates=(2048, 1024, 512, 256, 128)) -> int:
    """Largest lane-aligned tile that divides the vocab; 0 if none (caller
    falls back to the XLA path). GPT-2's padded 50304 fits 128; LLaMA's
    32000 fits 256."""
    for c in candidates:
        if v % c == 0:
            return c
    return 0


def _ce_fwd_kernel(x_ref, lab_ref, lse_ref, gold_ref, m_ref, l_ref, g_ref,
                   *, block_v: int, num_v: int):
    vi = pl.program_id(1)

    @pl.when(vi == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        g_ref[...] = jnp.zeros_like(g_ref)

    x = x_ref[...].astype(jnp.float32)  # (bt, bv)
    bt, bv = x.shape
    lab = lab_ref[...]  # (bt, 1) int32
    vpos = vi * block_v + jax.lax.broadcasted_iota(jnp.int32, (bt, bv), 1)
    m = m_ref[...]
    new_m = jnp.maximum(m, jnp.max(x, axis=1))
    corr = jnp.exp(jnp.where(m == NEG_INF, NEG_INF, m - new_m))
    l_ref[...] = l_ref[...] * corr + jnp.sum(jnp.exp(x - new_m[:, None]),
                                             axis=1)
    m_ref[...] = new_m
    # the gold logit lands in exactly one vocab tile per row
    g_ref[...] += jnp.sum(jnp.where(vpos == lab, x, 0.0), axis=1)

    @pl.when(vi == num_v - 1)
    def _fin():
        lse_ref[...] = (m_ref[...]
                        + jnp.log(jnp.maximum(l_ref[...], 1e-30)))[:, None]
        gold_ref[...] = g_ref[...][:, None]


def _ce_bwd_kernel(x_ref, lab_ref, lse_ref, a_ref, b_ref, dx_ref,
                   *, block_v: int):
    """dlogits = softmax * a - onehot * b, per (row, vocab-tile)."""
    vi = pl.program_id(1)
    x = x_ref[...].astype(jnp.float32)
    bt, bv = x.shape
    lab = lab_ref[...]
    vpos = vi * block_v + jax.lax.broadcasted_iota(jnp.int32, (bt, bv), 1)
    p = jnp.exp(x - lse_ref[...])
    dx = p * a_ref[...] - jnp.where(vpos == lab, b_ref[...], 0.0)
    dx_ref[...] = dx.astype(dx_ref.dtype)


@functools.partial(jax.jit, static_argnames=("block_t", "block_v",
                                             "interpret"))
def _ce_fwd_call(logits, labels2d, *, block_t, block_v, interpret):
    T, V = logits.shape
    num_t, num_v = T // block_t, V // block_v
    return pl.pallas_call(
        functools.partial(_ce_fwd_kernel, block_v=block_v, num_v=num_v),
        grid=(num_t, num_v),
        in_specs=[
            pl.BlockSpec((block_t, block_v), lambda t, v: (t, v)),
            pl.BlockSpec((block_t, 1), lambda t, v: (t, 0)),
        ],
        out_specs=[
            pl.BlockSpec((block_t, 1), lambda t, v: (t, 0)),
            pl.BlockSpec((block_t, 1), lambda t, v: (t, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((T, 1), jnp.float32),  # lse
            jax.ShapeDtypeStruct((T, 1), jnp.float32),  # gold logit
        ],
        scratch_shapes=[
            pltpu.VMEM((block_t,), jnp.float32),
            pltpu.VMEM((block_t,), jnp.float32),
            pltpu.VMEM((block_t,), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(logits, labels2d)


@functools.partial(jax.jit, static_argnames=("block_t", "block_v",
                                             "interpret"))
def _ce_bwd_call(logits, labels2d, lse, a, b, *, block_t, block_v,
                 interpret):
    T, V = logits.shape
    return pl.pallas_call(
        functools.partial(_ce_bwd_kernel, block_v=block_v),
        grid=(T // block_t, V // block_v),
        in_specs=[
            pl.BlockSpec((block_t, block_v), lambda t, v: (t, v)),
            pl.BlockSpec((block_t, 1), lambda t, v: (t, 0)),
            pl.BlockSpec((block_t, 1), lambda t, v: (t, 0)),
            pl.BlockSpec((block_t, 1), lambda t, v: (t, 0)),
            pl.BlockSpec((block_t, 1), lambda t, v: (t, 0)),
        ],
        out_specs=pl.BlockSpec((block_t, block_v), lambda t, v: (t, v)),
        out_shape=jax.ShapeDtypeStruct((T, V), logits.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel")),
        interpret=interpret,
    )(logits, labels2d, lse, a, b)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5))
def _ce_rows(logits, labels2d, z_loss, block_t, block_v, interpret):
    lse, gold = _ce_fwd_call(logits, labels2d, block_t=block_t,
                             block_v=block_v, interpret=interpret)
    nll = lse[:, 0] - gold[:, 0]
    if z_loss:
        nll = nll + z_loss * jnp.square(lse[:, 0])
    return nll


def _ce_rows_fwd(logits, labels2d, z_loss, block_t, block_v, interpret):
    lse, gold = _ce_fwd_call(logits, labels2d, block_t=block_t,
                             block_v=block_v, interpret=interpret)
    nll = lse[:, 0] - gold[:, 0]
    if z_loss:
        nll = nll + z_loss * jnp.square(lse[:, 0])
    return nll, (logits, labels2d, lse)


def _ce_rows_bwd(z_loss, block_t, block_v, interpret, res, g):
    logits, labels2d, lse = res
    g2 = g[:, None].astype(jnp.float32)
    a = g2 * (1.0 + 2.0 * z_loss * lse) if z_loss else g2
    dx = _ce_bwd_call(logits, labels2d, lse, a, g2, block_t=block_t,
                      block_v=block_v, interpret=interpret)
    return dx, np.zeros(labels2d.shape, dtype=jax.dtypes.float0)


_ce_rows.defvjp(_ce_rows_fwd, _ce_rows_bwd)


def fused_ce_nll(logits: jax.Array, labels: jax.Array, *,
                 z_loss: float = 0.0, interpret: bool = False,
                 block_t: int = 256) -> jax.Array | None:
    """Per-token NLL via the fused kernel, or None when the shape cannot
    tile (caller uses the XLA path). logits [..., V] any leading dims,
    labels matching leading dims."""
    V = logits.shape[-1]
    lead = logits.shape[:-1]
    T = int(np.prod(lead)) if lead else 1
    bv = fit_vocab_block(V)
    bt = block_t
    while bt > 8 and T % bt:
        bt //= 2
    if not bv or T % bt:
        return None
    # Mosaic only exists on TPU; anywhere else (CPU tests, smoke runs) the
    # kernel runs in interpret mode so the flag is safe on any backend
    interpret = interpret or jax.default_backend() != "tpu"
    nll = _ce_rows(logits.reshape(T, V),
                   labels.reshape(T, 1).astype(jnp.int32),
                   float(z_loss), bt, bv, interpret)
    return nll.reshape(lead)
