"""Pallas flash attention for TPU: causal, GQA-aware, online-softmax.

Replaces the reference's external flash-attn CUDA ops (SURVEY §2 native-code
checklist item 4; installed by galvatron/scripts/flash_attn_ops_install.sh)
with a TPU kernel: per (batch, q-head, q-block) grid cell the kernel streams
key/value blocks through VMEM with the usual running-max/normalizer
accumulation, so the [S, S] score matrix never touches HBM and the MXU sees
[block_q, d] x [d, block_k] tiles.

Layout: q [B, N, S, D], k/v [B, K, S, D] (heads-major so a grid cell's tiles
are contiguous); GQA maps q-head n to kv-head n // (N // K) in the index map.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = float(jnp.finfo(jnp.float32).min)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, block_q: int, block_k: int,
                  seq_len: int, causal: bool, scale: float):
    qi = pl.program_id(2)
    q = q_ref[0, 0].astype(jnp.float32) * scale  # [block_q, D]
    d = q.shape[-1]

    m = jnp.full((block_q,), NEG_INF, jnp.float32)
    l = jnp.zeros((block_q,), jnp.float32)
    acc = jnp.zeros((block_q, d), jnp.float32)

    num_k = seq_len // block_k
    if causal:
        # blocks past the diagonal contribute nothing; bound the loop
        last = (qi * block_q + block_q - 1) // block_k + 1
    else:
        last = num_k

    def body(ki, carry):
        m, l, acc = carry
        k = k_ref[0, 0, pl.dslice(ki * block_k, block_k), :].astype(
            jnp.float32)
        v = v_ref[0, 0, pl.dslice(ki * block_k, block_k), :].astype(
            jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)  # [bq, bk]
        if causal:
            qpos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kpos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(qpos >= kpos, s, NEG_INF)
        block_max = jnp.max(s, axis=1)
        new_m = jnp.maximum(m, block_max)
        corr = jnp.exp(jnp.where(m == NEG_INF, NEG_INF, m - new_m))
        p = jnp.exp(s - new_m[:, None])
        p = jnp.where(s == NEG_INF, 0.0, p)
        new_l = l * corr + jnp.sum(p, axis=1)
        new_acc = acc * corr[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return new_m, new_l, new_acc

    m, l, acc = jax.lax.fori_loop(0, last, body, (m, l, acc))
    out = acc / jnp.maximum(l, 1e-20)[:, None]
    o_ref[0, 0] = out.astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("causal", "block_q", "block_k",
                                             "interpret"))
def flash_attention_hmajor(
    q: jax.Array,  # [B, N, S, D]
    k: jax.Array,  # [B, K, S, D]
    v: jax.Array,
    *,
    causal: bool = True,
    block_q: int = 256,
    block_k: int = 256,
    interpret: bool = False,
) -> jax.Array:
    B, N, S, D = q.shape
    K = k.shape[1]
    G = N // K
    block_q = min(block_q, S)
    block_k = min(block_k, S)
    if S % block_q or S % block_k:
        raise ValueError(f"seq {S} must divide by blocks {block_q}/{block_k}")
    grid = (B, N, S // block_q)
    kernel = functools.partial(
        _flash_kernel, block_q=block_q, block_k=block_k, seq_len=S,
        causal=causal, scale=1.0 / math.sqrt(D))
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, D), lambda b, n, qi: (b, n, qi, 0)),
            pl.BlockSpec((1, 1, S, D), lambda b, n, qi: (b, n // G, 0, 0)),
            pl.BlockSpec((1, 1, S, D), lambda b, n, qi: (b, n // G, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, D),
                               lambda b, n, qi: (b, n, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((B, N, S, D), q.dtype),
        interpret=interpret,
    )(q, k, v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash_with_vjp(q, k, v, causal, interpret):
    qh = q.transpose(0, 2, 1, 3)
    kh = k.transpose(0, 2, 1, 3)
    vh = v.transpose(0, 2, 1, 3)
    out = flash_attention_hmajor(qh, kh, vh, causal=causal,
                                 interpret=interpret)
    return out.transpose(0, 2, 1, 3)


def _flash_fwd(q, k, v, causal, interpret):
    return _flash_with_vjp(q, k, v, causal, interpret), (q, k, v)


def _flash_bwd(causal, interpret, res, g):
    # Backward recomputes through the dense reference core (the standard
    # remat trade: forward stays O(block) in VMEM via the Pallas kernel, the
    # backward matches XLA's own attention gradient). A fused flash backward
    # kernel is a later optimization.
    from hetu_galvatron_tpu.models.modules import xla_sdpa

    q, k, v = res
    _, vjp = jax.vjp(lambda a, b, c: xla_sdpa(a, b, c, causal=causal),
                     q, k, v)
    return vjp(g)


_flash_with_vjp.defvjp(_flash_fwd, _flash_bwd)


def flash_sdpa(q, k, v, *, causal: bool = True, interpret: bool = False):
    """Drop-in sdpa_fn for modules.apply_attention: [B, S, N, D] layout in
    and out; differentiable (forward via the Pallas kernel, backward via the
    dense-core recompute)."""
    return _flash_with_vjp(q, k, v, causal, interpret)
