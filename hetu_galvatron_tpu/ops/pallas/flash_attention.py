"""Pallas flash attention for TPU: causal, GQA-aware, online-softmax.

Replaces the reference's external flash-attn CUDA ops (SURVEY §2 native-code
checklist item 4; installed by galvatron/scripts/flash_attn_ops_install.sh)
with a TPU kernel: the grid runs (batch, q-head, q-block, k-block) with the
k-block axis innermost, so each k/v tile is DMA'd into VMEM on demand while
running-max/normalizer/accumulator scratch persists across k-steps — the
[S, S] score matrix never exists and VMEM holds only O(block) tiles, so
sequence length is bounded by HBM, not VMEM.

Layout: q [B, N, S, D], k/v [B, K, S, D] (heads-major so a grid cell's tiles
are contiguous); GQA maps q-head n to kv-head n // (N // K) in the index map.
Backward runs through the dense reference core (remat); a fused backward
kernel is a later optimization.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = float(jnp.finfo(jnp.float32).min)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref, *,
                  block_q: int, block_k: int, num_k: int, causal: bool,
                  scale: float):
    qi = pl.program_id(2)
    ki = pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # blocks entirely past the causal diagonal contribute nothing
    diag_last = (qi * block_q + block_q - 1) // block_k if causal else num_k

    @pl.when(ki <= diag_last)
    def _update():
        q = q_ref[0, 0].astype(jnp.float32) * scale  # [bq, D]
        k = k_ref[0, 0].astype(jnp.float32)  # [bk, D]
        v = v_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            qpos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kpos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(qpos >= kpos, s, NEG_INF)
        m = m_ref[...]
        block_max = jnp.max(s, axis=1)
        new_m = jnp.maximum(m, block_max)
        corr = jnp.exp(jnp.where(m == NEG_INF, NEG_INF, m - new_m))
        p = jnp.exp(s - new_m[:, None])
        p = jnp.where(s == NEG_INF, 0.0, p)
        m_ref[...] = new_m
        l_ref[...] = l_ref[...] * corr + jnp.sum(p, axis=1)
        acc_ref[...] = acc_ref[...] * corr[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == num_k - 1)
    def _finalize():
        out = acc_ref[...] / jnp.maximum(l_ref[...], 1e-20)[:, None]
        o_ref[0, 0] = out.astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("causal", "block_q", "block_k",
                                             "interpret"))
def flash_attention_hmajor(
    q: jax.Array,  # [B, N, S, D]
    k: jax.Array,  # [B, K, S, D]
    v: jax.Array,
    *,
    causal: bool = True,
    block_q: int = 256,
    block_k: int = 256,
    interpret: bool = False,
) -> jax.Array:
    B, N, S, D = q.shape
    K = k.shape[1]
    G = N // K
    block_q = min(block_q, S)
    block_k = min(block_k, S)
    if S % block_q or S % block_k:
        raise ValueError(f"seq {S} must divide by blocks {block_q}/{block_k}")
    num_k = S // block_k
    grid = (B, N, S // block_q, num_k)  # k-block axis innermost
    kernel = functools.partial(
        _flash_kernel, block_q=block_q, block_k=block_k, num_k=num_k,
        causal=causal, scale=1.0 / math.sqrt(D))
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, D),
                         lambda b, n, qi, ki: (b, n, qi, 0)),
            pl.BlockSpec((1, 1, block_k, D),
                         lambda b, n, qi, ki: (b, n // G, ki, 0)),
            pl.BlockSpec((1, 1, block_k, D),
                         lambda b, n, qi, ki: (b, n // G, ki, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, D),
                               lambda b, n, qi, ki: (b, n, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((B, N, S, D), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q,), jnp.float32),
            pltpu.VMEM((block_q,), jnp.float32),
            pltpu.VMEM((block_q, D), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash_with_vjp(q, k, v, causal, interpret):
    qh = q.transpose(0, 2, 1, 3)
    kh = k.transpose(0, 2, 1, 3)
    vh = v.transpose(0, 2, 1, 3)
    out = flash_attention_hmajor(qh, kh, vh, causal=causal,
                                 interpret=interpret)
    return out.transpose(0, 2, 1, 3)


def _flash_fwd(q, k, v, causal, interpret):
    return _flash_with_vjp(q, k, v, causal, interpret), (q, k, v)


def _flash_bwd(causal, interpret, res, g):
    # Backward recomputes through the dense reference core (the standard
    # remat trade: forward stays O(block) in VMEM via the Pallas kernel, the
    # backward matches XLA's own attention gradient). A fused flash backward
    # kernel is a later optimization.
    from hetu_galvatron_tpu.models.modules import xla_sdpa

    q, k, v = res
    _, vjp = jax.vjp(lambda a, b, c: xla_sdpa(a, b, c, causal=causal),
                     q, k, v)
    return vjp(g)


_flash_with_vjp.defvjp(_flash_fwd, _flash_bwd)


def flash_sdpa(q, k, v, *, causal: bool = True, interpret: bool = False):
    """Drop-in sdpa_fn for modules.apply_attention: [B, S, N, D] layout in
    and out; differentiable (forward via the Pallas kernel, backward via the
    dense-core recompute)."""
    return _flash_with_vjp(q, k, v, causal, interpret)
