"""Pallas flash attention for TPU: causal, GQA-aware, online-softmax.

Replaces the reference's external flash-attn CUDA ops (SURVEY §2 native-code
checklist item 4; installed by galvatron/scripts/flash_attn_ops_install.sh)
with a TPU kernel: the grid runs (batch, q-head, q-block, k-block) with the
k-block axis innermost, so each k/v tile is DMA'd into VMEM on demand while
running-max/normalizer/accumulator scratch persists across k-steps — the
[S, S] score matrix never exists and VMEM holds only O(block) tiles, so
sequence length is bounded by HBM, not VMEM.

Layout: q [B, N, S, D], k/v [B, K, S, D] (heads-major so a grid cell's tiles
are contiguous); GQA maps q-head n to kv-head n // (N // K) in the index map.
The backward is fused too (dq and dk/dv kernels recompute p per tile from the
saved logsumexp), so neither direction materializes [S, S].
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# pin-compat: the CompilerParams dataclass was named TPUCompilerParams on
# older jax releases (this toolchain's pin); same fields either way
_CompilerParams = getattr(pltpu, "CompilerParams",
                          getattr(pltpu, "TPUCompilerParams", None))

NEG_INF = float(jnp.finfo(jnp.float32).min)


def keep_mask(seed, bn, qpos, kpos, rate: float):
    """Deterministic counter-based dropout keep-mask (splitmix32 finalizer
    chain over global coordinates). Depends only on GLOBAL coordinates
    (seed, batch*heads index, q position, k position), so forward/backward
    kernels regenerate identical masks regardless of tile sizes — the same
    property the reference gets from flash-attn's saved philox state. Plain
    integer ops only: lowers under Mosaic AND interpret mode (pltpu.prng_*
    has no CPU lowering), and a pure-JAX caller over full index grids is
    the test reference. qpos/kpos are int32 arrays broadcastable to the
    mask shape; returns bool (True = keep).

    There is no sequence-length bound: qpos and kpos are mixed through
    SEPARATE finalizer rounds rather than a linear ``qpos * S + kpos``
    counter (which wrapped uint32 once S exceeded 2**16 and aliased masks
    between distant (qpos, kpos) pairs within one head — the PR 1 fix), so
    distinct coordinate pairs collide only by hash accident, like head
    streams. The old ``s_total`` parameter that rode along for call-site
    compatibility is gone."""
    import numpy as np

    # numpy scalar literals (NOT jnp arrays): closed-over jnp constants are
    # rejected by the pallas_call lowering
    u32 = jnp.uint32
    c = np.uint32

    def fin(x):  # splitmix32 finalizer (full avalanche)
        x = x ^ (x >> c(16))
        x = x * c(0x85EBCA6B)
        x = x ^ (x >> c(13))
        x = x * c(0xC2B2AE35)
        return x ^ (x >> c(16))

    # hash (seed, bn) into a per-head key FIRST: a linear bn*S^2 counter
    # would wrap every 2^32/S^2 heads and hand distant heads bit-identical
    # masks; after avalanche, head streams collide only by hash accident
    key = fin(seed.astype(u32) * c(0x9E3779B9) + bn.astype(u32))
    x = fin(fin(qpos.astype(u32) ^ key) ^ kpos.astype(u32))
    keep_prob = 1.0 - rate
    threshold = c(min(int(keep_prob * 2.0 ** 32), 2 ** 32 - 1))
    return x < threshold


def _tile_keep(seed_ref, bn, qi, ki, block_q: int, block_k: int,
               rate: float):
    qpos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    kpos = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    return keep_mask(seed_ref[0], bn, qpos, kpos, rate)


def _flash_kernel(q_ref, k_ref, v_ref, *rest,
                  block_q: int, block_k: int, num_k: int, causal: bool,
                  scale: float, has_seg: bool = False,
                  dropout_rate: float = 0.0):
    if dropout_rate > 0.0:
        seed_ref, rest = rest[0], rest[1:]
    else:
        seed_ref = None
    if has_seg:
        qseg_ref, kseg_ref, o_ref, lse_ref, m_ref, l_ref, acc_ref = rest
    else:
        qseg_ref = kseg_ref = None
        o_ref, lse_ref, m_ref, l_ref, acc_ref = rest
    qi = pl.program_id(2)
    ki = pl.program_id(3)
    # flat batch*heads index for the dropout mask; program_id must be read
    # at kernel top level (the interpret-mode executor does not rewrite it
    # inside pl.when bodies)
    bn = pl.program_id(0) * pl.num_programs(1) + pl.program_id(1)

    @pl.when(ki == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # blocks entirely past the causal diagonal contribute nothing
    diag_last = (qi * block_q + block_q - 1) // block_k if causal else num_k

    @pl.when(ki <= diag_last)
    def _update():
        q = q_ref[0, 0].astype(jnp.float32) * scale  # [bq, D]
        k = k_ref[0, 0].astype(jnp.float32)  # [bk, D]
        v = v_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            qpos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kpos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(qpos >= kpos, s, NEG_INF)
        if qseg_ref is not None:
            # packed documents: mask cross-segment pairs (reference
            # reset_attention_mask; same trailing-singleton layout as lse)
            s = jnp.where(qseg_ref[0, :, 0][:, None]
                          == kseg_ref[0, :, 0][None, :], s, NEG_INF)
        m = m_ref[...]
        block_max = jnp.max(s, axis=1)
        new_m = jnp.maximum(m, block_max)
        corr = jnp.exp(jnp.where(m == NEG_INF, NEG_INF, m - new_m))
        p = jnp.exp(s - new_m[:, None])
        p = jnp.where(s == NEG_INF, 0.0, p)
        m_ref[...] = new_m
        # the normalizer uses the UNdropped p: out = dropout(softmax(s)) @ v
        l_ref[...] = l_ref[...] * corr + jnp.sum(p, axis=1)
        if dropout_rate > 0.0:
            keep = _tile_keep(seed_ref, bn, qi, ki, block_q, block_k,
                              dropout_rate)
            p = jnp.where(keep, p / (1.0 - dropout_rate), 0.0)
        acc_ref[...] = acc_ref[...] * corr[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == num_k - 1)
    def _finalize():
        l = jnp.maximum(l_ref[...], 1e-20)
        o_ref[0, 0] = (acc_ref[...] / l[:, None]).astype(o_ref.dtype)
        # logsumexp per row, consumed by the backward kernels; stored with a
        # trailing singleton lane dim — Mosaic requires the last two block
        # dims to be (mult-of-8, mult-of-128) or equal to the array dims, so
        # a rank-3 (1, 1, block_q) lse block cannot lower on hardware
        lse_ref[0, 0] = (m_ref[...] + jnp.log(l))[:, None]


@functools.partial(jax.jit, static_argnames=("causal", "block_q", "block_k",
                                             "interpret", "dropout_rate"))
def flash_attention_hmajor(
    q: jax.Array,  # [B, N, S, D]
    k: jax.Array,  # [B, K, S, D]
    v: jax.Array,
    segments: "jax.Array | None" = None,  # [B, S] int32 (packed docs)
    dropout_seed: "jax.Array | None" = None,  # [1] int32 (attention dropout)
    *,
    causal: bool = True,
    block_q: int = 256,
    block_k: int = 256,
    interpret: bool = False,
    dropout_rate: float = 0.0,
) -> jax.Array:
    B, N, S, D = q.shape
    K = k.shape[1]
    Sk = k.shape[2]  # may differ from S (ring off-diagonal blocks)
    G = N // K
    block_q = min(block_q, S)
    block_k = min(block_k, Sk)
    if S % block_q or Sk % block_k:
        raise ValueError(
            f"seq {S}/{Sk} must divide by blocks {block_q}/{block_k}")
    if causal and Sk != S:
        raise ValueError("causal flash needs equal q/k lengths")
    if segments is not None and Sk != S:
        raise ValueError("segment masking needs equal q/k lengths")
    if dropout_rate > 0.0 and dropout_seed is None:
        raise ValueError("dropout_rate > 0 needs a dropout_seed")
    num_k = Sk // block_k
    grid = (B, N, S // block_q, num_k)  # k-block axis innermost
    has_seg = segments is not None
    kernel = functools.partial(
        _flash_kernel, block_q=block_q, block_k=block_k, num_k=num_k,
        causal=causal, scale=1.0 / math.sqrt(D), has_seg=has_seg,
        dropout_rate=dropout_rate)
    in_specs = [
        pl.BlockSpec((1, 1, block_q, D),
                     lambda b, n, qi, ki: (b, n, qi, 0)),
        pl.BlockSpec((1, 1, block_k, D),
                     lambda b, n, qi, ki: (b, n // G, ki, 0)),
        pl.BlockSpec((1, 1, block_k, D),
                     lambda b, n, qi, ki: (b, n // G, ki, 0)),
    ]
    operands = [q, k, v]
    if dropout_rate > 0.0:
        # kernel unpacks the seed ref FIRST from *rest (after q/k/v)
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        operands.append(dropout_seed.astype(jnp.int32).reshape(1))
    if has_seg:
        # [B, S, 1]: trailing singleton keeps Mosaic's (8, 128)-or-equal
        # tiling rule satisfied (same layout trick as lse)
        seg3 = segments.astype(jnp.int32)[:, :, None]
        in_specs += [
            pl.BlockSpec((1, block_q, 1), lambda b, n, qi, ki: (b, qi, 0)),
            pl.BlockSpec((1, block_k, 1), lambda b, n, qi, ki: (b, ki, 0)),
        ]
        operands += [seg3, seg3]
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, block_q, D),
                         lambda b, n, qi, ki: (b, n, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 1),
                         lambda b, n, qi, ki: (b, n, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, N, S, D), q.dtype),
            jax.ShapeDtypeStruct((B, N, S, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q,), jnp.float32),
            pltpu.VMEM((block_q,), jnp.float32),
            pltpu.VMEM((block_q, D), jnp.float32),
        ],
        # only the k-block axis carries loop state (the online softmax);
        # everything else may be reordered/partitioned by Mosaic
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(*operands)


def _flash_bwd_dkdv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                           *rest, block_q: int, block_k: int, num_q: int,
                           G: int, causal: bool, scale: float,
                           has_seg: bool = False,
                           dropout_rate: float = 0.0):
    """Grid (B, KV, kb, G, qb): accumulate dk/dv for one k/v tile across the
    G query heads of this kv head and all q blocks."""
    if dropout_rate > 0.0:
        seed_ref, rest = rest[0], rest[1:]
    else:
        seed_ref = None
    if has_seg:
        qseg_ref, kseg_ref, dk_ref, dv_ref, dk_acc, dv_acc = rest
    else:
        qseg_ref = kseg_ref = None
        dk_ref, dv_ref, dk_acc, dv_acc = rest
    kb = pl.program_id(2)
    g = pl.program_id(3)
    qb = pl.program_id(4)
    # flat head index n = kh*G + g (N = KV*G heads); top-level program_id
    bn = pl.program_id(0) * (pl.num_programs(1) * G) + pl.program_id(1) * G + g

    @pl.when((g == 0) & (qb == 0))
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    # q blocks entirely above the causal diagonal contribute nothing
    first_q = (kb * block_k) // block_q if causal else 0

    @pl.when(qb >= first_q)
    def _update():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0]  # (block_q, 1): broadcasts over block_k
        delta = delta_ref[0, 0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            qpos = qb * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kpos = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(qpos >= kpos, s, NEG_INF)
        if qseg_ref is not None:
            s = jnp.where(qseg_ref[0, :, 0][:, None]
                          == kseg_ref[0, :, 0][None, :], s, NEG_INF)
        p = jnp.exp(s - lse)
        p = jnp.where(s == NEG_INF, 0.0, p)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        pd = p
        if dropout_rate > 0.0:
            # mask is (qpos, kpos)-indexed; this kernel's tile is q=qb, k=kb
            keep = _tile_keep(seed_ref, bn, qb, kb, block_q, block_k,
                              dropout_rate)
            pd = jnp.where(keep, p / (1.0 - dropout_rate), 0.0)
            dp = jnp.where(keep, dp / (1.0 - dropout_rate), 0.0)
        dv_acc[...] += jax.lax.dot_general(
            pd, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        # delta = rowsum(dropout(P) . dP') = dO . O, so the flash delta
        # trick survives dropout unchanged
        ds = p * (dp - delta) * scale
        dk_acc[...] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when((g == G - 1) & (qb == num_q - 1))
    def _finalize():
        dk_ref[0, 0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[...].astype(dv_ref.dtype)


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         *rest, block_q: int, block_k: int,
                         num_k: int, causal: bool, scale: float,
                         has_seg: bool = False,
                         dropout_rate: float = 0.0):
    """Grid (B, N, qb, kb): accumulate dq for one q tile across k blocks."""
    if dropout_rate > 0.0:
        seed_ref, rest = rest[0], rest[1:]
    else:
        seed_ref = None
    if has_seg:
        qseg_ref, kseg_ref, dq_ref, dq_acc = rest
    else:
        qseg_ref = kseg_ref = None
        dq_ref, dq_acc = rest
    qb = pl.program_id(2)
    kb = pl.program_id(3)
    bn = pl.program_id(0) * pl.num_programs(1) + pl.program_id(1)

    @pl.when(kb == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    diag_last = (qb * block_q + block_q - 1) // block_k if causal else num_k

    @pl.when(kb <= diag_last)
    def _update():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0]  # (block_q, 1): broadcasts over block_k
        delta = delta_ref[0, 0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            qpos = qb * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kpos = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(qpos >= kpos, s, NEG_INF)
        if qseg_ref is not None:
            s = jnp.where(qseg_ref[0, :, 0][:, None]
                          == kseg_ref[0, :, 0][None, :], s, NEG_INF)
        p = jnp.exp(s - lse)
        p = jnp.where(s == NEG_INF, 0.0, p)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        if dropout_rate > 0.0:
            keep = _tile_keep(seed_ref, bn, qb, kb,
                              block_q, block_k, dropout_rate)
            dp = jnp.where(keep, dp / (1.0 - dropout_rate), 0.0)
        ds = p * (dp - delta) * scale
        dq_acc[...] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(kb == num_k - 1)
    def _finalize():
        dq_ref[0, 0] = dq_acc[...].astype(dq_ref.dtype)


@functools.partial(jax.jit, static_argnames=("causal", "block_q", "block_k",
                                             "interpret", "dropout_rate"))
def flash_attention_bwd_hmajor(
    q, k, v, o, lse, do, segments=None, dropout_seed=None, *,
    causal: bool = True,
    block_q: int = 256,
    block_k: int = 256,
    interpret: bool = False,
    dropout_rate: float = 0.0,
):
    """Fused flash backward (heads-major layouts): recomputes p from lse per
    tile, so nothing O(S^2) ever hits HBM. Returns (dq, dk, dv)."""
    B, N, S, D = q.shape
    KV = k.shape[1]
    Sk = k.shape[2]  # may differ from S (ring off-diagonal blocks)
    G = N // KV
    block_q = min(block_q, S)
    block_k = min(block_k, Sk)
    num_q = S // block_q
    num_k = Sk // block_k
    scale = 1.0 / math.sqrt(D)
    if causal and Sk != S:
        raise ValueError("causal flash needs equal q/k lengths")
    has_seg = segments is not None
    if has_seg and Sk != S:
        raise ValueError("segment masking needs equal q/k lengths")
    # (B, N, S, 1): same trailing-singleton layout as lse (Mosaic tiling)
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1,
                    keepdims=True)

    if dropout_rate > 0.0 and dropout_seed is None:
        raise ValueError("dropout_rate > 0 needs a dropout_seed")
    seed_arr = (dropout_seed.astype(jnp.int32).reshape(1)
                if dropout_rate > 0.0 else None)

    dkdv_in_specs = [
        pl.BlockSpec((1, 1, block_q, D),
                     lambda b, kh, kb, g, qb: (b, kh * G + g, qb, 0)),
        pl.BlockSpec((1, 1, block_k, D),
                     lambda b, kh, kb, g, qb: (b, kh, kb, 0)),
        pl.BlockSpec((1, 1, block_k, D),
                     lambda b, kh, kb, g, qb: (b, kh, kb, 0)),
        pl.BlockSpec((1, 1, block_q, D),
                     lambda b, kh, kb, g, qb: (b, kh * G + g, qb, 0)),
        pl.BlockSpec((1, 1, block_q, 1),
                     lambda b, kh, kb, g, qb: (b, kh * G + g, qb, 0)),
        pl.BlockSpec((1, 1, block_q, 1),
                     lambda b, kh, kb, g, qb: (b, kh * G + g, qb, 0)),
    ]
    dkdv_operands = [q, k, v, do, lse, delta]
    if dropout_rate > 0.0:
        dkdv_in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        dkdv_operands.append(seed_arr)
    if has_seg:
        seg3 = segments.astype(jnp.int32)[:, :, None]
        dkdv_in_specs += [
            pl.BlockSpec((1, block_q, 1),
                         lambda b, kh, kb, g, qb: (b, qb, 0)),
            pl.BlockSpec((1, block_k, 1),
                         lambda b, kh, kb, g, qb: (b, kb, 0)),
        ]
        dkdv_operands += [seg3, seg3]

    dkdv = pl.pallas_call(
        functools.partial(_flash_bwd_dkdv_kernel, block_q=block_q,
                          block_k=block_k, num_q=num_q, G=G, causal=causal,
                          scale=scale, has_seg=has_seg,
                          dropout_rate=dropout_rate),
        grid=(B, KV, num_k, G, num_q),
        in_specs=dkdv_in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, block_k, D),
                         lambda b, kh, kb, g, qb: (b, kh, kb, 0)),
            pl.BlockSpec((1, 1, block_k, D),
                         lambda b, kh, kb, g, qb: (b, kh, kb, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, KV, Sk, D), k.dtype),
            jax.ShapeDtypeStruct((B, KV, Sk, D), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, D), jnp.float32),
            pltpu.VMEM((block_k, D), jnp.float32),
        ],
        # dk/dv accumulate across the (g, qb) axes; kb tiles are independent
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary", "arbitrary")),
        interpret=interpret,
    )(*dkdv_operands)

    dq_in_specs = [
        pl.BlockSpec((1, 1, block_q, D),
                     lambda b, n, qb, kb: (b, n, qb, 0)),
        pl.BlockSpec((1, 1, block_k, D),
                     lambda b, n, qb, kb: (b, n // G, kb, 0)),
        pl.BlockSpec((1, 1, block_k, D),
                     lambda b, n, qb, kb: (b, n // G, kb, 0)),
        pl.BlockSpec((1, 1, block_q, D),
                     lambda b, n, qb, kb: (b, n, qb, 0)),
        pl.BlockSpec((1, 1, block_q, 1),
                     lambda b, n, qb, kb: (b, n, qb, 0)),
        pl.BlockSpec((1, 1, block_q, 1),
                     lambda b, n, qb, kb: (b, n, qb, 0)),
    ]
    dq_operands = [q, k, v, do, lse, delta]
    if dropout_rate > 0.0:
        dq_in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        dq_operands.append(seed_arr)
    if has_seg:
        dq_in_specs += [
            pl.BlockSpec((1, block_q, 1), lambda b, n, qb, kb: (b, qb, 0)),
            pl.BlockSpec((1, block_k, 1), lambda b, n, qb, kb: (b, kb, 0)),
        ]
        dq_operands += [seg3, seg3]
    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, block_q=block_q,
                          block_k=block_k, num_k=num_k, causal=causal,
                          scale=scale, has_seg=has_seg,
                          dropout_rate=dropout_rate),
        grid=(B, N, num_q, num_k),
        in_specs=dq_in_specs,
        out_specs=pl.BlockSpec((1, 1, block_q, D),
                               lambda b, n, qb, kb: (b, n, qb, 0)),
        out_shape=jax.ShapeDtypeStruct((B, N, S, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        # dq accumulates across k blocks only
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(*dq_operands)
    return dq, dkdv[0], dkdv[1]


# default tile sizes, overridable per call (swept on hardware by
# tools/tpu_flash_check.py)
DEFAULT_BLOCK_Q = 256
DEFAULT_BLOCK_K = 512


def fit_block(default: int, seq: int, floor: int = 128) -> int:
    """Largest block <= default that divides seq (halving from default, so
    the result keeps the mult-of-128 lane alignment Mosaic wants). Returns 0
    if nothing >= floor divides seq — caller falls back to the XLA core."""
    b = min(default, seq)
    while b >= floor:
        if seq % b == 0:
            return b
        b //= 2
    return 0


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def _flash_with_vjp(q, k, v, segments, dropout_seed, causal, interpret,
                    block_q, block_k, dropout_rate):
    qh = q.transpose(0, 2, 1, 3)
    kh = k.transpose(0, 2, 1, 3)
    vh = v.transpose(0, 2, 1, 3)
    out, _ = flash_attention_hmajor(qh, kh, vh, segments, dropout_seed,
                                    causal=causal, interpret=interpret,
                                    block_q=block_q, block_k=block_k,
                                    dropout_rate=dropout_rate)
    return out.transpose(0, 2, 1, 3)


def _flash_fwd(q, k, v, segments, dropout_seed, causal, interpret, block_q,
               block_k, dropout_rate):
    qh = q.transpose(0, 2, 1, 3)
    kh = k.transpose(0, 2, 1, 3)
    vh = v.transpose(0, 2, 1, 3)
    out, lse = flash_attention_hmajor(qh, kh, vh, segments, dropout_seed,
                                      causal=causal, interpret=interpret,
                                      block_q=block_q, block_k=block_k,
                                      dropout_rate=dropout_rate)
    return (out.transpose(0, 2, 1, 3),
            (qh, kh, vh, out, lse, segments, dropout_seed))


def _flash_bwd(causal, interpret, block_q, block_k, dropout_rate, res, g):
    qh, kh, vh, out, lse, segments, dropout_seed = res
    dq, dk, dv = flash_attention_bwd_hmajor(
        qh, kh, vh, out, lse, g.transpose(0, 2, 1, 3), segments,
        dropout_seed, causal=causal, interpret=interpret,
        block_q=block_q, block_k=block_k, dropout_rate=dropout_rate)
    return (dq.transpose(0, 2, 1, 3), dk.transpose(0, 2, 1, 3),
            dv.transpose(0, 2, 1, 3), None, None)  # int operands: no cotan


_flash_with_vjp.defvjp(_flash_fwd, _flash_bwd)


def seed_from_key(rng: jax.Array) -> jax.Array:
    """Fold a jax PRNG key into the [1] int32 seed the kernel's
    counter-based mask consumes."""
    return jax.random.randint(rng, (1,), 0, jnp.iinfo(jnp.int32).max,
                              dtype=jnp.int32)


def flash_sdpa(q, k, v, *, causal: bool = True, interpret: bool = False,
               block_q: int | None = None, block_k: int | None = None,
               segment_ids=None, dropout_rate: float = 0.0,
               dropout_rng=None):
    """Drop-in sdpa_fn for modules.apply_attention: [B, S, N, D] layout in
    and out; fully differentiable — forward and backward both run as fused
    Pallas kernels (backward recomputes p per tile from the saved
    logsumexp), so neither direction materializes [S, S].

    ``segment_ids`` [B, S] masks cross-document attention for packed
    samples (reference reset_attention_mask) inside the kernel — packed
    pretraining keeps flash speed instead of falling back to the dense core.

    ``dropout_rate`` > 0 (+ ``dropout_rng``) applies attention-probability
    dropout in-kernel via a counter-based mask over global (head, qpos,
    kpos) — the reference's flash-attn dropout variant. The mask derives
    from the key, not from jax.random's threefry, so flash-dropout
    trajectories are deterministic per seed but not bit-equal to the XLA
    core's (the reference's CUDA kernel has the same property vs torch).

    Block defaults are clamped to divisors of S (e.g. S=768 runs 256-wide
    k blocks even though the tuned default is 512)."""
    S = q.shape[1]
    seed = None
    if dropout_rate > 0.0:
        if dropout_rng is None:
            raise ValueError("flash dropout_rate > 0 needs dropout_rng")
        seed = seed_from_key(dropout_rng)
    return _flash_with_vjp(q, k, v, segment_ids, seed, causal, interpret,
                           block_q or fit_block(DEFAULT_BLOCK_Q, S) or S,
                           block_k or fit_block(DEFAULT_BLOCK_K, S) or S,
                           dropout_rate)


# the fwd + both bwd kernels mask cross-document tiles in-kernel
flash_sdpa.supports_segments = True
# in-kernel counter-based attention dropout (fwd + bwd regenerate the mask)
flash_sdpa.supports_dropout = True


def make_flash_sdpa(mesh, dp_axes=(), tp_axes=(), *, interpret: bool = False,
                    stage_axis=None):
    """Distributed flash attention: the kernel is a custom call XLA cannot
    auto-partition, so it runs under shard_map — batch sharded over dp,
    heads over tp, sequence local (attention needs the full sequence; cp
    layers use ring attention instead). Grad flows through the fused VJP
    inside the shard_map. ``segment_ids`` [B, S] ride as an extra batch-
    sharded operand so packed documents keep flash speed under SPMD.
    ``dropout_rate`` > 0 runs the in-kernel counter-based dropout; each
    shard folds its (dp, tp) mesh coordinates into the seed so masks
    decorrelate across the sharded batch/head dims.

    ``interpret=True`` (CPU tests / parity drills) also relaxes the block
    floor: a sequence no tile >= 128 divides runs as one whole-sequence
    block instead of silently falling back to the XLA core (matching
    ``flash_sdpa``'s ``or S`` default), so CPU drills exercise the real
    kernel arithmetic.

    ``stage_axis`` (the compiled 1F1B engine): q/k/v carry a leading
    ``[pp, ...]`` stacked stage dim sharded on that mesh axis; the
    shard_map spans the WHOLE mesh (pp included, full-manual) and each pp
    row runs its own stage's attention — this is how the Pallas kernel
    nests inside the fused single-program pipeline. ``dropout_rng`` is
    then a ``[pp]`` key array (one per stage lane, matching the host
    engine's per-(microbatch, stage) keys)."""
    from functools import partial as _partial

    from jax.sharding import PartitionSpec as P

    import jax

    spec = P(dp_axes or None, None, tp_axes or None, None)
    seg_spec = P(dp_axes or None, None)
    seed_spec = P()
    s_dim = 1
    if stage_axis is not None:
        spec = P(stage_axis, *spec)
        seg_spec = P(stage_axis, *seg_spec)
        seed_spec = P(stage_axis, None)
        s_dim = 2

    def _shard_seed(seed):
        idx = jnp.int32(0)
        for ax in tuple(dp_axes) + tuple(tp_axes):
            idx = idx * jnp.int32(mesh.shape[ax]) + jax.lax.axis_index(ax)
        return seed + idx * jnp.int32(-1640531527)  # 2654435761 as int32

    def _xla_fallback(q, k, v, causal, segment_ids, dropout_rate,
                      dropout_rng):
        from hetu_galvatron_tpu.models.modules import xla_sdpa

        if stage_axis is None:
            return xla_sdpa(q, k, v, causal=causal, segment_ids=segment_ids,
                            dropout_rate=dropout_rate,
                            dropout_rng=dropout_rng)
        # stacked operands: the XLA core is weight-free, so a plain vmap
        # over the stage lane reproduces the per-stage host arithmetic
        core = _partial(xla_sdpa, causal=causal, dropout_rate=dropout_rate)
        if dropout_rng is not None:
            return jax.vmap(lambda a, b, c, s, r: core(
                a, b, c, segment_ids=s, dropout_rng=r))(
                q, k, v, segment_ids, dropout_rng) \
                if segment_ids is not None else jax.vmap(
                    lambda a, b, c, r: core(a, b, c, dropout_rng=r))(
                    q, k, v, dropout_rng)
        if segment_ids is not None:
            return jax.vmap(lambda a, b, c, s: core(a, b, c,
                                                    segment_ids=s))(
                q, k, v, segment_ids)
        return jax.vmap(lambda a, b, c: core(a, b, c))(q, k, v)

    def sdpa(q, k, v, *, causal=True, segment_ids=None,
             dropout_rate: float = 0.0, dropout_rng=None):
        S = q.shape[s_dim]
        bq = fit_block(DEFAULT_BLOCK_Q, S)
        bk = fit_block(DEFAULT_BLOCK_K, S)
        if interpret:
            # interpret mode has no lane-alignment constraint: run the
            # whole sequence as one block rather than losing the kernel
            bq, bk = bq or S, bk or S
        # shapes the kernel can't tile (no lane-aligned block divides the
        # sequence, or cross-attention with different q/kv lengths): XLA core
        if not bq or not bk or k.shape[s_dim] != S:
            return _xla_fallback(q, k, v, causal, segment_ids, dropout_rate,
                                 dropout_rng)
        seed = None
        if dropout_rate > 0.0:
            if dropout_rng is None:
                raise ValueError("flash dropout_rate > 0 needs dropout_rng")
            if stage_axis is not None:
                # one independent counter stream per stage lane
                seed = jax.vmap(seed_from_key)(dropout_rng)
            else:
                seed = seed_from_key(dropout_rng)

        # one shard_map over a dynamic operand list; the optional operands
        # are rebuilt into keywords inside (custom_vjp args stay positional)
        has_seg, has_seed = segment_ids is not None, seed is not None
        in_specs = [spec, spec, spec]
        operands = [q, k, v]
        if has_seg:
            in_specs.append(seg_spec)
            operands.append(segment_ids)
        if has_seed:
            in_specs.append(seed_spec)
            operands.append(seed)

        def local(a, b, c, *rest):
            s = rest[0] if has_seg else None
            sd = _shard_seed(rest[-1]) if has_seed else None
            return _flash_with_vjp(a, b, c, s, sd, causal, interpret,
                                   bq, bk, dropout_rate)

        from jax.experimental.shard_map import shard_map

        from hetu_galvatron_tpu.ops.overlap import staged_lane

        # each pp row holds its stage's [1, ...] lane (the shared
        # compiled-engine adapter squeezes it around the kernel)
        local = staged_lane(local, stage_axis is not None)

        fn = shard_map(local, mesh=mesh, in_specs=tuple(in_specs),
                       out_specs=spec, check_rep=False)
        return fn(*operands)

    sdpa.supports_segments = True
    sdpa.supports_dropout = True
    return sdpa
