"""Ring attention: context-parallel causal attention over the cp mesh axes.

Capability parity with the reference's zigzag ring flash attention
(runtime/transformer/attention_impl.py:481-905 ``ZigzagRingFlashAttention`` +
``RingComm`` batched isend/irecv): each cp rank holds a contiguous sequence
block of q/k/v; k/v blocks rotate around the ring while a streaming (online
softmax) accumulator folds each block's contribution — memory per chip stays
O(S/cp) and the ring transfers ride ICI via `lax.ppermute` instead of NCCL
p2p.

Two sequence layouts are supported: contiguous blocks (trivial GSPMD
boundaries; block-causal masking; per-rank compute imbalance bounded by cp)
and the reference's zigzag layout (``zigzag=True``: each rank holds global
half-blocks r and 2cp-1-r, equalizing unmasked work across the ring).
"""

from __future__ import annotations

import math
from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

NEG_INF = float(jnp.finfo(jnp.float32).min)


def _block_scores(q, k, scale):
    """[B,Sq,K,G,D] x [B,Sk,K,D] -> [B,K,G,Sq,Sk] fp32."""
    return jnp.einsum("bskgd,btkd->bkgst", q, k,
                      preferred_element_type=jnp.float32) * scale


def _positions(rank, length, cp, zigzag):
    """Global sequence positions of a rank's local block. Contiguous layout:
    [rank*L, rank*L + L). Zigzag layout (reference redistribute.py:5-41):
    the local block is the concatenation of global half-blocks rank and
    2cp-1-rank, balancing causal work across the ring."""
    i = jnp.arange(length)
    if not zigzag:
        return rank * length + i
    h = length // 2
    return jnp.where(i < h,
                     rank * h + i,
                     (2 * cp - 1 - rank) * h + (i - h))


def _fold_block(step, acc, *, q, k, v, my_idx, cp, causal, zigzag):
    """Fold the key/value block currently held (from rank
    (my_idx - step) mod cp) into the streaming softmax accumulator."""
    o, m, l = acc
    B, Sq, K, G, D = q.shape
    src_block = (my_idx - step) % cp
    scores = _block_scores(q, k, 1.0 / math.sqrt(D))  # [B,K,G,Sq,Sk]
    if causal:
        qpos = _positions(my_idx, Sq, cp, zigzag)[:, None]
        kpos = _positions(src_block, k.shape[1], cp, zigzag)[None, :]
        scores = jnp.where(qpos >= kpos, scores, NEG_INF)
    block_max = jnp.max(scores, axis=-1)  # [B,K,G,Sq]
    new_m = jnp.maximum(m, block_max)
    # guard fully-masked rows: exp(NEG_INF - NEG_INF) would be 1
    correction = jnp.exp(jnp.where(m == NEG_INF, NEG_INF, m - new_m))
    p = jnp.exp(scores - new_m[..., None])
    p = jnp.where(scores == NEG_INF, 0.0, p)
    new_l = l * correction + jnp.sum(p, axis=-1)
    pv = jnp.einsum("bkgst,btkd->bkgsd", p.astype(v.dtype), v,
                    preferred_element_type=jnp.float32)
    new_o = o * correction[..., None] + pv
    return new_o, new_m, new_l


def _ring_body(step, carry, *, q, my_idx, cp, causal, zigzag, axis):
    """One ring step: fold the current block, then rotate k/v onward."""
    o, m, l, k, v = carry
    o, m, l = _fold_block(step, (o, m, l), q=q, k=k, v=v, my_idx=my_idx,
                          cp=cp, causal=causal, zigzag=zigzag)
    perm = [(i, (i + 1) % cp) for i in range(cp)]
    k = jax.lax.ppermute(k, axis, perm)
    v = jax.lax.ppermute(v, axis, perm)
    return o, m, l, k, v


def _ring_attention_local(q, k, v, *, axis, causal, zigzag=False):
    """Per-shard kernel under shard_map: q/k/v are the local sequence blocks
    [B, S/cp, N|K, D]."""
    cp = jax.lax.axis_size(axis)
    my_idx = jax.lax.axis_index(axis)
    B, Sq, N, D = q.shape
    K = k.shape[2]
    G = N // K
    qg = q.reshape(B, Sq, K, G, D)
    o = jnp.zeros((B, K, G, Sq, D), jnp.float32)
    m = jnp.full((B, K, G, Sq), NEG_INF, jnp.float32)
    l = jnp.zeros((B, K, G, Sq), jnp.float32)
    body = partial(_ring_body, q=qg, my_idx=my_idx, cp=cp,
                   causal=causal, zigzag=zigzag, axis=axis)
    # cp-1 fold+rotate steps, then the final fold without the wasted rotate
    o, m, l, k, v = jax.lax.fori_loop(0, cp - 1, body, (o, m, l, k, v))
    o, m, l = _fold_block(cp - 1, (o, m, l), q=qg, k=k, v=v, my_idx=my_idx,
                          cp=cp, causal=causal, zigzag=zigzag)
    o = o / jnp.maximum(l, 1e-20)[..., None]
    return o.transpose(0, 3, 1, 2, 4).reshape(B, Sq, N, D).astype(q.dtype)


def make_ring_sdpa(
    mesh: Mesh,
    cp_axes: Tuple[str, ...],
    dp_axes: Tuple[str, ...] = (),
    tp_axes: Tuple[str, ...] = (),
    zigzag: bool = False,
):
    """sdpa_fn for modules.apply_attention: reshards q/k/v so the sequence
    lives on the cp axes, runs the ring kernel under shard_map, and hands the
    seq-sharded output back to GSPMD (the reference reaches its ring kernel
    through the per-layer dispatch at attention.py:664-720).

    ``zigzag=True`` re-lays the sequence into the reference's balanced
    causal order around the kernel (RoPE is applied upstream, so permuting
    post-RoPE q/k/v is position-safe). Balancing costs one all-to-all-ish
    reshard at entry/exit; pushing the zigzag layout out to the dataloader
    (get_batch zigzag slice, reference utils.py:295) removes that cost and
    is the long-sequence deployment mode."""
    if not cp_axes:
        raise ValueError("ring attention needs at least one cp axis")
    axis = cp_axes if len(cp_axes) > 1 else cp_axes[0]
    spec = P(dp_axes or None, cp_axes, tp_axes or None, None)
    cp = 1
    for a in cp_axes:
        cp *= mesh.shape[a]

    def sdpa(q, k, v, *, causal=True):
        S = q.shape[1]
        if S % cp:
            raise ValueError(f"sequence {S} not divisible by cp {cp}")
        if zigzag and S % (2 * cp):
            raise ValueError(
                f"zigzag layout needs sequence {S} divisible by 2*cp "
                f"= {2 * cp} (two half-blocks per rank)")
        fn = jax.shard_map(
            partial(_ring_attention_local, axis=axis, causal=causal,
                    zigzag=zigzag),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False)
        if zigzag:
            q, k, v = (zigzag_layout(t, cp) for t in (q, k, v))
        out = fn(q, k, v)
        return zigzag_unlayout(out, cp) if zigzag else out

    return sdpa


def zigzag_layout(x: jax.Array, cp: int, axis: int = 1) -> jax.Array:
    """Re-layout a sequence into zigzag block order (block i and 2cp-1-i per
    rank) — the reference's balanced causal layout (redistribute.py:5-41).
    Provided for interchange with zigzag-trained checkpoints/plans."""
    blocks = jnp.split(x, 2 * cp, axis=axis)
    out = []
    for r in range(cp):
        out.append(blocks[r])
        out.append(blocks[2 * cp - 1 - r])
    return jnp.concatenate(out, axis=axis)


def zigzag_unlayout(x: jax.Array, cp: int, axis: int = 1) -> jax.Array:
    """Inverse of :func:`zigzag_layout`."""
    blocks = jnp.split(x, 2 * cp, axis=axis)
    out = [None] * (2 * cp)
    for r in range(cp):
        out[r] = blocks[2 * r]
        out[2 * cp - 1 - r] = blocks[2 * r + 1]
    return jnp.concatenate(out, axis=axis)
