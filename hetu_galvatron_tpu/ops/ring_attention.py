"""Ring attention: context-parallel causal attention over the cp mesh axes.

Capability parity with the reference's zigzag ring flash attention
(runtime/transformer/attention_impl.py:481-905 ``ZigzagRingFlashAttention`` +
``RingComm`` batched isend/irecv): each cp rank holds a contiguous sequence
block of q/k/v; k/v blocks rotate around the ring while a streaming (online
softmax) accumulator folds each block's contribution — memory per chip stays
O(S/cp) and the ring transfers ride ICI via `lax.ppermute` instead of NCCL
p2p.

Two sequence layouts are supported: contiguous blocks (trivial GSPMD
boundaries; block-causal masking; per-rank compute imbalance bounded by cp)
and the reference's zigzag layout (``zigzag=True``: each rank holds global
half-blocks r and 2cp-1-r, equalizing unmasked work across the ring).
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

NEG_INF = float(jnp.finfo(jnp.float32).min)


def _block_scores(q, k, scale):
    """[B,Sq,K,G,D] x [B,Sk,K,D] -> [B,K,G,Sq,Sk] fp32."""
    return jnp.einsum("bskgd,btkd->bkgst", q, k,
                      preferred_element_type=jnp.float32) * scale


def _positions(rank, length, cp, zigzag):
    """Global sequence positions of a rank's local block. Contiguous layout:
    [rank*L, rank*L + L). Zigzag layout (reference redistribute.py:5-41):
    the local block is the concatenation of global half-blocks rank and
    2cp-1-rank, balancing causal work across the ring."""
    i = jnp.arange(length)
    if not zigzag:
        return rank * length + i
    h = length // 2
    return jnp.where(i < h,
                     rank * h + i,
                     (2 * cp - 1 - rank) * h + (i - h))


def _fold_block(step, acc, *, q, k, v, my_idx, cp, causal, zigzag,
                qseg=None, kseg=None):
    """Fold the key/value block currently held (from rank
    (my_idx - step) mod cp) into the streaming softmax accumulator.
    ``qseg`` [B, Sq] / ``kseg`` [B, Sk] block-diagonalize packed documents
    (reference reset_attention_mask); kseg rotates with its k/v block."""
    o, m, l = acc
    B, Sq, K, G, D = q.shape
    src_block = (my_idx - step) % cp
    scores = _block_scores(q, k, 1.0 / math.sqrt(D))  # [B,K,G,Sq,Sk]
    if causal:
        qpos = _positions(my_idx, Sq, cp, zigzag)[:, None]
        kpos = _positions(src_block, k.shape[1], cp, zigzag)[None, :]
        scores = jnp.where(qpos >= kpos, scores, NEG_INF)
    if qseg is not None:
        same = (qseg[:, None, None, :, None]
                == kseg[:, None, None, None, :])  # [B,1,1,Sq,Sk]
        scores = jnp.where(same, scores, NEG_INF)
    block_max = jnp.max(scores, axis=-1)  # [B,K,G,Sq]
    new_m = jnp.maximum(m, block_max)
    # guard fully-masked rows: exp(NEG_INF - NEG_INF) would be 1
    correction = jnp.exp(jnp.where(m == NEG_INF, NEG_INF, m - new_m))
    p = jnp.exp(scores - new_m[..., None])
    p = jnp.where(scores == NEG_INF, 0.0, p)
    new_l = l * correction + jnp.sum(p, axis=-1)
    pv = jnp.einsum("bkgst,btkd->bkgsd", p.astype(v.dtype), v,
                    preferred_element_type=jnp.float32)
    new_o = o * correction[..., None] + pv
    return new_o, new_m, new_l


def _ring_body(step, carry, *, q, qseg, my_idx, cp, causal, zigzag, axis):
    """One ring step: fold the current block, then rotate k/v (and the
    k-side segment ids) onward."""
    o, m, l, k, v, kseg = carry
    o, m, l = _fold_block(step, (o, m, l), q=q, k=k, v=v, my_idx=my_idx,
                          cp=cp, causal=causal, zigzag=zigzag,
                          qseg=qseg, kseg=kseg)
    perm = [(i, (i + 1) % cp) for i in range(cp)]
    k = jax.lax.ppermute(k, axis, perm)
    v = jax.lax.ppermute(v, axis, perm)
    if kseg is not None:
        kseg = jax.lax.ppermute(kseg, axis, perm)
    return o, m, l, k, v, kseg


def _ring_attention_local(q, k, v, seg=None, *, axis, cp, causal,
                          zigzag=False):
    """Per-shard kernel under shard_map: q/k/v are the local sequence blocks
    [B, S/cp, N|K, D]; ``seg`` [B, S/cp] packed-document segment ids.
    ``cp`` is the static ring size (this jax pin has no jax.lax.axis_size;
    the caller knows it from the mesh anyway)."""
    my_idx = jax.lax.axis_index(axis)
    B, Sq, N, D = q.shape
    K = k.shape[2]
    G = N // K
    qg = q.reshape(B, Sq, K, G, D)
    o = jnp.zeros((B, K, G, Sq, D), jnp.float32)
    m = jnp.full((B, K, G, Sq), NEG_INF, jnp.float32)
    l = jnp.zeros((B, K, G, Sq), jnp.float32)
    body = partial(_ring_body, q=qg, qseg=seg, my_idx=my_idx, cp=cp,
                   causal=causal, zigzag=zigzag, axis=axis)
    # cp-1 fold+rotate steps, then the final fold without the wasted rotate
    # (seg=None is a structure-only pytree leaf: one loop serves both cases)
    o, m, l, k, v, kseg = jax.lax.fori_loop(
        0, cp - 1, body, (o, m, l, k, v, seg))
    o, m, l = _fold_block(cp - 1, (o, m, l), q=qg, k=k, v=v, my_idx=my_idx,
                          cp=cp, causal=causal, zigzag=zigzag,
                          qseg=seg, kseg=kseg)
    o = o / jnp.maximum(l, 1e-20)[..., None]
    return o.transpose(0, 3, 1, 2, 4).reshape(B, Sq, N, D).astype(q.dtype)


# ---------------------------------------------------------------------------
# flash-inside-the-ring: each ring step runs the Pallas flash kernel on the
# currently-held k/v block; per-block (o, lse) pairs merge in log space.
# Mirrors the reference's zigzag ring flash (attention_impl.py:564-905), where
# each step issues a flash_attn call on a full or half block:
#   * diagonal step (src == my): plain causal flash on the local layout
#     (for zigzag the local [half r | half 2cp-1-r] order IS causal order);
#   * src < my: every q row attends the earlier block — non-causal flash on
#     the full k (contiguous) or its first half (zigzag: the second half of
#     an earlier rank's block is LATER than all local rows... see _positions);
#   * src > my: contiguous ranks skip entirely; zigzag ranks attend with the
#     local second half only (global half-block 2cp-1-my is after everything
#     rank src holds).
# The backward replays the ring with the final (o, lse): the flash backward
# recomputes p per tile from the global logsumexp, so per-step dk/dv are
# exact partial sums; they accumulate in buffers that rotate in lockstep
# with k/v and arrive home after cp rotations (the reference's reverse-ring
# send of dk/dv).
# ---------------------------------------------------------------------------


def _fit_or_die(seq: int, floor: int) -> Tuple[int, int]:
    from hetu_galvatron_tpu.ops.pallas.flash_attention import (
        DEFAULT_BLOCK_K,
        DEFAULT_BLOCK_Q,
        fit_block,
    )

    bq = fit_block(DEFAULT_BLOCK_Q, seq, floor)
    bk = fit_block(DEFAULT_BLOCK_K, seq, floor)
    if not bq or not bk:
        raise ValueError(f"no flash block >= {floor} divides seq {seq}")
    return bq, bk


def ring_flash_blocks_fit(s_local: int, zigzag: bool, floor: int) -> bool:
    """Whether the flash-in-ring path can tile this local sequence length
    (callers fall back to the dense XLA ring otherwise)."""
    from hetu_galvatron_tpu.ops.pallas.flash_attention import (
        DEFAULT_BLOCK_K,
        DEFAULT_BLOCK_Q,
        fit_block,
    )

    seqs = [s_local] + ([s_local // 2] if zigzag else [])
    return all(s > 0
               and fit_block(DEFAULT_BLOCK_Q, s, floor)
               and fit_block(DEFAULT_BLOCK_K, s, floor) for s in seqs)


def _fa_block(q, k, v, causal, interpret, floor):
    """Forward flash on one (q, k/v) block pair; heads-major [B,N,S,D]."""
    from hetu_galvatron_tpu.ops.pallas.flash_attention import (
        flash_attention_hmajor,
    )

    bq, _ = _fit_or_die(q.shape[2], floor)
    _, bk = _fit_or_die(k.shape[2], floor)
    o, lse = flash_attention_hmajor(q, k, v, None, causal=causal,
                                    block_q=bq, block_k=bk,
                                    interpret=interpret)
    return o.astype(jnp.float32), lse


def _fa_block_bwd(q, k, v, o, lse, do, causal, interpret, floor):
    """Backward flash on one block pair -> (dq, dk, dv) fp32."""
    from hetu_galvatron_tpu.ops.pallas.flash_attention import (
        flash_attention_bwd_hmajor,
    )

    bq, _ = _fit_or_die(q.shape[2], floor)
    _, bk = _fit_or_die(k.shape[2], floor)
    dq, dk, dv = flash_attention_bwd_hmajor(
        q, k, v, o, lse, do, None, causal=causal,
        block_q=bq, block_k=bk, interpret=interpret)
    return (dq.astype(jnp.float32), dk.astype(jnp.float32),
            dv.astype(jnp.float32))


def _combine_blocks(o, lse, oi, lsei):
    """Merge two normalized flash outputs (o fp32 [B,N,S,D], lse
    [B,N,S,1]): o = o*exp(lse-m)/denom + oi*exp(lsei-m)/denom."""
    m = jnp.maximum(lse, lsei)
    m_safe = jnp.where(m == NEG_INF, 0.0, m)
    a = jnp.where(lse == NEG_INF, 0.0, jnp.exp(lse - m_safe))
    ai = jnp.where(lsei == NEG_INF, 0.0, jnp.exp(lsei - m_safe))
    denom = jnp.maximum(a + ai, 1e-38)
    new_lse = jnp.where(a + ai > 0.0, m_safe + jnp.log(denom), NEG_INF)
    return o * (a / denom) + oi * (ai / denom), new_lse


def _rotate(ts, axis, cp):
    perm = [(i, (i + 1) % cp) for i in range(cp)]
    return tuple(jax.lax.ppermute(t, axis, perm) for t in ts)


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _ring_flash_local(q, k, v, axis, cp, causal, zigzag, interpret, floor):
    out, _ = _ring_flash_fwd(q, k, v, axis, cp, causal, zigzag, interpret,
                             floor)
    return out


def _ring_flash_fwd(q, k, v, axis, cp, causal, zigzag, interpret, floor):
    """q [B,N,S,D], k/v [B,K,S,D] heads-major local blocks under shard_map."""
    my = jax.lax.axis_index(axis)
    B, N, S, D = q.shape
    K = k.shape[1]
    half = S // 2
    kt, vt = k, v
    o, lse = _fa_block(q, kt, vt, causal, interpret, floor)  # diagonal step
    for t in range(1, cp):
        kt, vt = _rotate((kt, vt), axis, cp)
        src = (my - t) % cp
        if not causal:
            oi, lsei = _fa_block(q, kt, vt, False, interpret, floor)
        elif zigzag:
            def _earlier(kb, vb):
                # src holds global half-blocks (src, 2cp-1-src); only the
                # FIRST half (src < my) is in the local rows' past
                return _fa_block(q, kb[:, :, :half], vb[:, :, :half],
                                 False, interpret, floor)

            def _later(kb, vb):
                # src > my: only local half 2cp-1-my (rows half:) is after
                # everything rank src holds
                ob, lb = _fa_block(q[:, :, half:], kb, vb,
                                   False, interpret, floor)
                return (
                    jnp.concatenate(
                        [jnp.zeros((B, N, half, D), jnp.float32), ob], 2),
                    jnp.concatenate(
                        [jnp.full((B, N, half, 1), NEG_INF, jnp.float32),
                         lb], 2),
                )

            oi, lsei = jax.lax.cond(src < my, _earlier, _later, kt, vt)
        else:
            def _earlier(kb, vb):
                return _fa_block(q, kb, vb, False, interpret, floor)

            def _later(kb, vb):
                return (jnp.zeros((B, N, S, D), jnp.float32),
                        jnp.full((B, N, S, 1), NEG_INF, jnp.float32))

            oi, lsei = jax.lax.cond(src < my, _earlier, _later, kt, vt)
        o, lse = _combine_blocks(o, lse, oi, lsei)
    out = o.astype(q.dtype)
    return out, (q, k, v, out, lse)


def _ring_flash_bwd(axis, cp, causal, zigzag, interpret, floor, res, do):
    """Ring replay: per-step flash backward against the final (o, lse);
    dk/dv partial sums rotate with k/v and arrive home after cp steps."""
    q, k, v, o, lse = res
    my = jax.lax.axis_index(axis)
    B, N, S, D = q.shape
    K = k.shape[1]
    half = S // 2
    dq = jnp.zeros((B, N, S, D), jnp.float32)
    dk_acc = jnp.zeros((B, K, S, D), jnp.float32)
    dv_acc = jnp.zeros((B, K, S, D), jnp.float32)
    kt, vt = k, v
    for t in range(cp):
        src = (my - t) % cp
        if t == 0:
            dq_c, dk_c, dv_c = _fa_block_bwd(q, kt, vt, o, lse, do, causal,
                                             interpret, floor)
        elif not causal:
            dq_c, dk_c, dv_c = _fa_block_bwd(q, kt, vt, o, lse, do, False,
                                             interpret, floor)
        elif zigzag:
            def _earlier(kb, vb):
                dqb, dkb, dvb = _fa_block_bwd(
                    q, kb[:, :, :half], vb[:, :, :half], o, lse, do,
                    False, interpret, floor)
                pad = jnp.zeros((B, K, half, D), jnp.float32)
                return (dqb, jnp.concatenate([dkb, pad], 2),
                        jnp.concatenate([dvb, pad], 2))

            def _later(kb, vb):
                dqb, dkb, dvb = _fa_block_bwd(
                    q[:, :, half:], kb, vb, o[:, :, half:],
                    lse[:, :, half:], do[:, :, half:],
                    False, interpret, floor)
                pad = jnp.zeros((B, N, half, D), jnp.float32)
                return jnp.concatenate([pad, dqb], 2), dkb, dvb

            dq_c, dk_c, dv_c = jax.lax.cond(src < my, _earlier, _later,
                                            kt, vt)
        else:
            def _earlier(kb, vb):
                return _fa_block_bwd(q, kb, vb, o, lse, do, False,
                                     interpret, floor)

            def _later(kb, vb):
                return (jnp.zeros((B, N, S, D), jnp.float32),
                        jnp.zeros((B, K, S, D), jnp.float32),
                        jnp.zeros((B, K, S, D), jnp.float32))

            dq_c, dk_c, dv_c = jax.lax.cond(src < my, _earlier, _later,
                                            kt, vt)
        dq = dq + dq_c
        dk_acc = dk_acc + dk_c
        dv_acc = dv_acc + dv_c
        # rotate every step (cp total): a contribution for block b added at
        # step t undergoes cp - t further rotations -> lands on rank b
        kt, vt, dk_acc, dv_acc = _rotate((kt, vt, dk_acc, dv_acc), axis, cp)
    return dq.astype(q.dtype), dk_acc.astype(k.dtype), dv_acc.astype(v.dtype)


_ring_flash_local.defvjp(_ring_flash_fwd, _ring_flash_bwd)


def _ring_flash_sdpa_local(q, k, v, *, axis, cp, causal, zigzag, interpret,
                           floor):
    """shard_map body: [B, S/cp, N|K, D] in/out (matches
    :func:`_ring_attention_local`); flash kernels want heads-major."""
    qh, kh, vh = (t.transpose(0, 2, 1, 3) for t in (q, k, v))
    out = _ring_flash_local(qh, kh, vh, axis, cp, causal, zigzag, interpret,
                            floor)
    return out.transpose(0, 2, 1, 3)


def make_ring_sdpa(
    mesh: Mesh,
    cp_axes: Tuple[str, ...],
    dp_axes: Tuple[str, ...] = (),
    tp_axes: Tuple[str, ...] = (),
    zigzag: bool = False,
    use_flash: bool = False,
    interpret: bool = False,
    data_zigzagged: bool = False,
    stage_axis: Optional[str] = None,
):
    """sdpa_fn for modules.apply_attention: reshards q/k/v so the sequence
    lives on the cp axes, runs the ring kernel under shard_map, and hands the
    seq-sharded output back to GSPMD (the reference reaches its ring kernel
    through the per-layer dispatch at attention.py:664-720).

    ``zigzag=True`` re-lays the sequence into the reference's balanced
    causal order around the kernel (RoPE is applied upstream, so permuting
    post-RoPE q/k/v is position-safe). Balancing costs one all-to-all-ish
    reshard at entry/exit; pushing the zigzag layout out to the dataloader
    (get_batch zigzag slice, reference utils.py:295) removes that cost and
    is the long-sequence deployment mode.

    ``use_flash=True`` runs the Pallas flash kernel inside each ring step
    (the reference's flash-in-ring, attention_impl.py:564-905) instead of
    the dense per-block XLA fold — O(block) memory per step at MXU speed.
    Falls back to the dense fold per call when no lane-aligned flash block
    tiles the local sequence. ``interpret=True`` is for CPU tests.

    ``data_zigzagged=True`` (with ``zigzag=True``) declares the inputs
    ALREADY in zigzag order — the dataloader applied the layout
    (runtime/dataloader.py zigzag_cp_batches) — so the entry/exit
    permutes are skipped entirely: zero reshard cost per call.

    ``stage_axis`` (the compiled 1F1B engine): q/k/v carry a leading
    ``[pp, ...]`` stacked stage dim sharded on that mesh axis — the
    shard_map spans the whole mesh (full-manual, pp included) and each pp
    row rings only its own stage's blocks over the cp axes."""
    if not cp_axes:
        raise ValueError("ring attention needs at least one cp axis")
    if data_zigzagged and not zigzag:
        raise ValueError("data_zigzagged requires zigzag=True (the kernel "
                         "must mask by zigzag global positions)")
    axis = cp_axes if len(cp_axes) > 1 else cp_axes[0]
    spec = P(dp_axes or None, cp_axes, tp_axes or None, None)
    s_dim = 1
    if stage_axis is not None:
        spec = P(stage_axis, *spec)
        s_dim = 2
    cp = 1
    for a in cp_axes:
        cp *= mesh.shape[a]

    def sdpa(q, k, v, *, causal=True, segment_ids=None):
        S = q.shape[s_dim]
        if S % cp:
            raise ValueError(f"sequence {S} not divisible by cp {cp}")
        if zigzag and S % (2 * cp):
            raise ValueError(
                f"zigzag layout needs sequence {S} divisible by 2*cp "
                f"= {2 * cp} (two half-blocks per rank)")
        floor = 8 if interpret else 128
        has_seg = segment_ids is not None
        if (use_flash and not has_seg
                and ring_flash_blocks_fit(S // cp, zigzag, floor)):
            local = partial(_ring_flash_sdpa_local, axis=axis, cp=cp,
                            causal=causal, zigzag=zigzag,
                            interpret=interpret, floor=floor)
        else:
            # packed documents ride the dense fold: k-side segment ids
            # rotate with their k/v block; the flash-in-ring kernels would
            # need unequal-length q/k segment operands (future work)
            local = partial(_ring_attention_local, axis=axis, cp=cp,
                            causal=causal, zigzag=zigzag)
        from hetu_galvatron_tpu.ops.overlap import staged_lane

        # each pp row holds its stage's [1, ...] lane: squeeze it, run the
        # ring, restore it on the way out (the shared compiled-engine
        # adapter)
        inner = staged_lane(local, stage_axis is not None)
        local_scoped = lambda *a, _f=inner: _cp_scoped(_f, *a)
        seg_spec = P(spec[0], cp_axes) if stage_axis is None \
            else P(stage_axis, spec[1], cp_axes)
        in_specs = (spec, spec, spec) + ((seg_spec,) if has_seg else ())
        from jax.experimental.shard_map import shard_map

        fn = shard_map(
            local_scoped,
            mesh=mesh, in_specs=in_specs, out_specs=spec,
            check_rep=False)
        relayout = zigzag and not data_zigzagged
        if relayout:
            q, k, v = (zigzag_layout(t, cp, axis=s_dim) for t in (q, k, v))
            if has_seg:
                segment_ids = zigzag_layout(segment_ids, cp, axis=s_dim)
        out = fn(q, k, v, *((segment_ids,) if has_seg else ()))
        return zigzag_unlayout(out, cp, axis=s_dim) if relayout else out

    sdpa.supports_segments = True
    return sdpa


def _cp_scoped(fn, *args):
    """Run a ring body under the ``cp_ring`` HLO-metadata scope so trace
    attribution can bill its collective-permutes to the cp component even
    when they share one program with pp stage rotations
    (observability/trace_analysis.py)."""
    with jax.named_scope("cp_ring"):
        return fn(*args)


def zigzag_layout(x: jax.Array, cp: int, axis: int = 1) -> jax.Array:
    """Re-layout a sequence into zigzag block order (block i and 2cp-1-i per
    rank) — the reference's balanced causal layout (redistribute.py:5-41).
    Provided for interchange with zigzag-trained checkpoints/plans."""
    blocks = jnp.split(x, 2 * cp, axis=axis)
    out = []
    for r in range(cp):
        out.append(blocks[r])
        out.append(blocks[2 * cp - 1 - r])
    return jnp.concatenate(out, axis=axis)


def zigzag_unlayout(x: jax.Array, cp: int, axis: int = 1) -> jax.Array:
    """Inverse of :func:`zigzag_layout`."""
    blocks = jnp.split(x, 2 * cp, axis=axis)
    out = [None] * (2 * cp)
    for r in range(cp):
        out[r] = blocks[2 * r]
        out[2 * cp - 1 - r] = blocks[2 * r + 1]
    return jnp.concatenate(out, axis=axis)
