"""Decomposed tensor-parallel collective matmuls: ring all-gather/
reduce-scatter fused with the projection they feed, so the transfer hides
behind dependent compute.

Why: under GSPMD auto-partitioning every Megatron-SP layer runs
<all-gather over sequence> -> <matmul> (column-parallel) and
<matmul> -> <reduce-scatter over sequence> (row-parallel) as two
dependent ops — the collective sits on the critical path. The decomposed
form splits the sequence into one chunk per tp rank and `lax.ppermute`s
chunks around the ring while each rank multiplies the chunk it already
holds; XLA's latency-hiding scheduler overlaps the permute DMA with the
chunk matmul, so only the first hop is exposed ("The Big Send-off",
PAPERS.md; TransformerEngine's ring-exchange ag/rs overlap is the GPU
analogue). The α-β cost model (cost_model/cost.py) prices this as the
``tp_overlap`` discount.

Discipline: full-manual ``shard_map`` over the layer's (dp, tp) mesh axes —
the same shard_map style ``runtime/compiled_pipeline.py`` and the flash
kernel wrapper use — with custom VJPs so the backward runs the transposed
collectives ring-overlapped too:

* :func:`make_ag_matmul` (column-parallel, e.g. qkv / MLP fc1):
  x [B, S/tp, H] (sequence-sharded) x w [H, F/tp] -> y [B, S, F/tp];
  bwd: dx = ring-reduce-scatter(dy @ w^T), dw = ring-ag(x)^T @ dy.
* :func:`make_matmul_rs` (row-parallel, e.g. attn out / MLP fc2):
  h [B, S, F/tp] x w [F/tp, H] -> y [B, S/tp, H] (partial products ring
  reduce-scattered as they finish); bwd mirrors with the ag ring.

Both are tolerance-identical to the GSPMD reference (the einsum paths in
``models/modules.py``): fp32 accumulation, per-chunk matmuls, only the
reduction ORDER across tp ranks differs (tests/kernels/test_tp_overlap.py
pins fwd+bwd parity at tp∈{2,4} in bf16 and f32).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P


from hetu_galvatron_tpu.runtime.mesh import axes_size as _axis_prod

# HLO-metadata marker for the ring ppermutes (jax.named_scope): trace
# attribution (observability/trace_analysis.py) uses it to bill tp-ring
# collective-permute time to the tp component instead of pp/cp when the
# rings run inside the compiled pipeline's single program
TP_RING_SCOPE = "tp_ring"


def _ring_perm(tp: int):
    return [(i, (i + 1) % tp) for i in range(tp)]


def _with_stage(spec: P, stage_axis: Optional[str]) -> P:
    """Prepend the compiled pipeline's stage axis to a kernel spec: the
    caller's operands carry a leading ``[pp, ...]`` stage dim (one stage per
    ``pp`` mesh row), which the kernel treats as a local size-1 lane."""
    return P(stage_axis, *spec) if stage_axis else spec


def staged_lane(fn: Callable, stage: bool) -> Callable:
    """Adapt a local shard_map body to the optional leading stage lane of
    the compiled 1F1B engine: inside the (full-manual) shard_map each
    operand arrives as ``[1, ...]`` — one stage's slice — so the body runs
    on the squeezed view and the lane dim is restored on the way out. The
    squeeze/expand pair is linear, so the custom VJPs underneath transpose
    through it unchanged. Shared by every stage-capable kernel factory
    (ring matmuls here, ring attention, Ulysses, flash)."""
    if not stage:
        return fn

    def wrapped(*args):
        out = fn(*(a[0] for a in args))
        if isinstance(out, tuple):
            return tuple(o[None] for o in out)
        return out[None]

    return wrapped


_staged = staged_lane  # module-internal alias used by the builders below


# ---------------------------------------------------------------------------
# per-shard ring kernels (run inside shard_map; axes/tp are static)
# ---------------------------------------------------------------------------


def _ring_ag_matmul(x, w, axes, tp, with_gathered=False):
    """Local: x [B, C, H] (this rank's sequence chunk), w [H, Fl] ->
    y [B, tp*C, Fl] fp32 (plus the assembled [B, tp*C, H] gather when
    ``with_gathered`` — the chunks pass through anyway, and saving them
    lets the backward form dw with ZERO extra collectives, exactly like
    GSPMD saving the gathered activation). Step t multiplies the chunk
    currently held (origin rank (r - t) % tp) while the ppermute ships it
    onward — the rotation is independent of the matmul, so the scheduler
    overlaps them."""
    r = jax.lax.axis_index(axes)
    B, C, _ = x.shape
    out = jnp.zeros((B, tp * C, w.shape[1]), jnp.float32)
    gathered = jnp.zeros((B, tp * C, x.shape[2]), x.dtype) \
        if with_gathered else None
    perm = _ring_perm(tp)
    cur = x
    for t in range(tp):
        c = (r - t) % tp  # origin chunk id of the block currently held
        part = jnp.einsum("bch,hf->bcf", cur, w,
                          preferred_element_type=jnp.float32)
        out = jax.lax.dynamic_update_slice(out, part, (0, c * C, 0))
        if with_gathered:
            gathered = jax.lax.dynamic_update_slice(
                gathered, cur, (0, c * C, 0))
        if t < tp - 1:
            cur = jax.lax.ppermute(cur, axes, perm)
    return (out, gathered) if with_gathered else out


def _ring_matmul_rs(h, w, axes, tp):
    """Local: h [B, S, Fl], w [Fl, Hd] -> this rank's sequence chunk of
    sum_over_ranks(h @ w): [B, S/tp, Hd] fp32. The partial-sum accumulator
    for chunk c starts at rank (c+1) % tp and rides the ring, each rank
    adding its partial product for that chunk as it passes through; the
    add and the next hop overlap with the following chunk's matmul."""
    r = jax.lax.axis_index(axes)
    B, S, _ = h.shape
    C = S // tp
    perm = _ring_perm(tp)
    acc = None
    for t in range(tp):
        c = (r - 1 - t) % tp  # chunk whose accumulator this rank holds now
        blk = jax.lax.dynamic_slice(h, (0, c * C, 0), (B, C, h.shape[2]))
        part = jnp.einsum("bcf,fh->bch", blk, w,
                          preferred_element_type=jnp.float32)
        acc = part if acc is None else (
            jax.lax.ppermute(acc, axes, perm) + part)
    return acc  # after tp-1 hops the chunk lands on its home rank r


def _ring_ag_grads(dy, w, h, axes, tp):
    """Fused backward ring for matmul_rs: ONE rotation of the cotangent
    chunk dy [B, C, Hd] serves both outputs —
    dh [B, tp*C, Fl] = all-gather(dy) @ w^T placed chunk-wise, and
    dw [Fl, Hd] = h^T @ all-gather(dy) accumulated chunk-wise."""
    r = jax.lax.axis_index(axes)
    B, C, _ = dy.shape
    Fl = w.shape[0]
    dh = jnp.zeros((B, tp * C, Fl), jnp.float32)
    dw = jnp.zeros((Fl, dy.shape[2]), jnp.float32)
    perm = _ring_perm(tp)
    cur = dy
    for t in range(tp):
        c = (r - t) % tp
        part = jnp.einsum("bch,fh->bcf", cur, w,
                          preferred_element_type=jnp.float32)
        dh = jax.lax.dynamic_update_slice(dh, part, (0, c * C, 0))
        h_c = jax.lax.dynamic_slice(h, (0, c * C, 0), (B, C, Fl))
        dw = dw + jnp.einsum("bcf,bch->fh", h_c, cur,
                             preferred_element_type=jnp.float32)
        if t < tp - 1:
            cur = jax.lax.ppermute(cur, axes, perm)
    return dh, dw


# ---------------------------------------------------------------------------
# public builders
# ---------------------------------------------------------------------------


def make_ag_matmul(mesh: Mesh, dp_axes: Tuple[str, ...],
                   tp_axes: Tuple[str, ...],
                   stage_axis: Optional[str] = None) -> Callable:
    """Column-parallel overlapped matmul: callable(x, w) with GLOBAL arrays
    x [B, S, H] (batch over dp, sequence over tp) and w [H, F] (columns over
    tp), returning fp32 [B, S, F] (features over tp) — the drop-in
    replacement for ``all-gather(seq) -> einsum`` in apply_attention /
    apply_mlp.

    ``stage_axis`` (the compiled 1F1B engine): operands and result carry a
    leading ``[pp, ...]`` stacked stage dim sharded on that mesh axis —
    x [pp, B, S, H], w [pp, H, F] — and each pp mesh row rings only its own
    stage's slice. This is how the kernels run INSIDE the fused pipeline
    program: one full-manual shard_map spanning the whole mesh, no nesting."""
    tp = _axis_prod(mesh, tp_axes)
    axes = tuple(tp_axes)

    @jax.custom_vjp
    def local(x, w):
        with jax.named_scope(TP_RING_SCOPE):
            return _ring_ag_matmul(x, w, axes, tp)

    def fwd(x, w):
        # save the ring-gathered activation (it passes through anyway):
        # dw then needs no collectives at all, matching GSPMD's
        # save-the-gather backward
        with jax.named_scope(TP_RING_SCOPE):
            y, x_full = _ring_ag_matmul(x, w, axes, tp, with_gathered=True)
        return y, (x_full, w)

    def bwd(res, dy):
        x_full, w = res
        # dx = reduce-scatter(dy @ w^T) over sequence — the rs ring with
        # the transposed weight; dw is collective-free off the saved gather
        # (the gather keeps x's dtype, so the casts below stay primal-exact)
        with jax.named_scope(TP_RING_SCOPE):
            dx = _ring_matmul_rs(dy, w.T, axes, tp).astype(x_full.dtype)
        dw = jnp.einsum("bsh,bsf->hf", x_full, dy,
                        preferred_element_type=jnp.float32).astype(w.dtype)
        return dx, dw

    local.defvjp(fwd, bwd)
    x_spec = _with_stage(P(dp_axes or None, axes, None), stage_axis)
    w_spec = _with_stage(P(None, axes), stage_axis)
    y_spec = _with_stage(P(dp_axes or None, None, axes), stage_axis)
    return shard_map(_staged(local, stage_axis is not None), mesh,
                     in_specs=(x_spec, w_spec),
                     out_specs=y_spec, check_rep=False)


def make_ag_matmul_pair(mesh: Mesh, dp_axes: Tuple[str, ...],
                        tp_axes: Tuple[str, ...],
                        stage_axis: Optional[str] = None) -> Callable:
    """Gated-MLP fc1: callable(x, w_gate, w_up) -> (gate, up), both fp32
    [B, S, F] with features over tp, from ONE ring rotation (each held
    chunk multiplies both weight halves). Splitting the FUSED [H, 2F]
    product globally instead would reshard the ACTIVATION: a tp shard of
    the fused layout holds contiguous columns of [gate | up], so the
    global split crosses shard boundaries and GSPMD pays a per-token
    collective to realign. The pair form moves that realignment to the
    weight halves instead (slicing the fused param re-shards each [H, F]
    half over tp) — weights are a per-step constant-size transfer, far
    smaller than the [B, S, F] activations, and the bench showed the swap
    is worth 30-50%% of step time at tp4/swiglu."""
    tp = _axis_prod(mesh, tp_axes)
    axes = tuple(tp_axes)

    def _pair_body(x, wg, wu, with_gathered=False):
        r = jax.lax.axis_index(axes)
        B, C, _ = x.shape
        g = jnp.zeros((B, tp * C, wg.shape[1]), jnp.float32)
        u = jnp.zeros((B, tp * C, wu.shape[1]), jnp.float32)
        gathered = jnp.zeros((B, tp * C, x.shape[2]), x.dtype) \
            if with_gathered else None
        perm = _ring_perm(tp)
        cur = x
        for t in range(tp):
            c = (r - t) % tp
            g = jax.lax.dynamic_update_slice(
                g, jnp.einsum("bch,hf->bcf", cur, wg,
                              preferred_element_type=jnp.float32),
                (0, c * C, 0))
            u = jax.lax.dynamic_update_slice(
                u, jnp.einsum("bch,hf->bcf", cur, wu,
                              preferred_element_type=jnp.float32),
                (0, c * C, 0))
            if with_gathered:
                gathered = jax.lax.dynamic_update_slice(
                    gathered, cur, (0, c * C, 0))
            if t < tp - 1:
                cur = jax.lax.ppermute(cur, axes, perm)
        return g, u, gathered

    @jax.custom_vjp
    def local(x, wg, wu):
        with jax.named_scope(TP_RING_SCOPE):
            g, u, _ = _pair_body(x, wg, wu)
        return g, u

    def fwd(x, wg, wu):
        with jax.named_scope(TP_RING_SCOPE):
            g, u, x_full = _pair_body(x, wg, wu, with_gathered=True)
        return (g, u), (x_full, wg, wu)

    def bwd(res, dys):
        x_full, wg, wu = res
        dg, du = dys
        # dx: ONE rs ring whose per-chunk partial sums both halves'
        # products; dw halves are collective-free off the saved gather
        with jax.named_scope(TP_RING_SCOPE):
            r = jax.lax.axis_index(axes)
            B, S, _ = dg.shape
            C = S // tp
            perm = _ring_perm(tp)
            acc = None
            for t in range(tp):
                c = (r - 1 - t) % tp
                g_c = jax.lax.dynamic_slice(dg, (0, c * C, 0),
                                            (B, C, dg.shape[2]))
                u_c = jax.lax.dynamic_slice(du, (0, c * C, 0),
                                            (B, C, du.shape[2]))
                part = (jnp.einsum("bcf,hf->bch", g_c, wg,
                                   preferred_element_type=jnp.float32)
                        + jnp.einsum("bcf,hf->bch", u_c, wu,
                                     preferred_element_type=jnp.float32))
                acc = part if acc is None else (
                    jax.lax.ppermute(acc, axes, perm) + part)
        dx = acc.astype(x_full.dtype)
        dwg = jnp.einsum("bsh,bsf->hf", x_full, dg,
                         preferred_element_type=jnp.float32).astype(wg.dtype)
        dwu = jnp.einsum("bsh,bsf->hf", x_full, du,
                         preferred_element_type=jnp.float32).astype(wu.dtype)
        return dx, dwg, dwu

    local.defvjp(fwd, bwd)
    x_spec = _with_stage(P(dp_axes or None, axes, None), stage_axis)
    w_spec = _with_stage(P(None, axes), stage_axis)
    y_spec = _with_stage(P(dp_axes or None, None, axes), stage_axis)
    return shard_map(_staged(local, stage_axis is not None), mesh,
                     in_specs=(x_spec, w_spec, w_spec),
                     out_specs=(y_spec, y_spec), check_rep=False)


def make_matmul_rs(mesh: Mesh, dp_axes: Tuple[str, ...],
                   tp_axes: Tuple[str, ...],
                   stage_axis: Optional[str] = None) -> Callable:
    """Row-parallel overlapped matmul: callable(h, w) with GLOBAL arrays
    h [B, S, F] (features over tp) and w [F, H] (rows over tp), returning
    fp32 [B, S, H] (sequence over tp) — replacing
    ``einsum -> reduce-scatter(seq)``. ``stage_axis``: see
    :func:`make_ag_matmul`."""
    tp = _axis_prod(mesh, tp_axes)
    axes = tuple(tp_axes)

    @jax.custom_vjp
    def local(h, w):
        with jax.named_scope(TP_RING_SCOPE):
            return _ring_matmul_rs(h, w, axes, tp)

    def fwd(h, w):
        with jax.named_scope(TP_RING_SCOPE):
            return _ring_matmul_rs(h, w, axes, tp), (h, w)

    def bwd(res, dy):
        h, w = res
        # one fused ring rotation of dy yields both dh = all-gather(dy) @
        # w^T and dw = h^T @ all-gather(dy)
        with jax.named_scope(TP_RING_SCOPE):
            dh, dw = _ring_ag_grads(dy, w, h, axes, tp)
        return dh.astype(h.dtype), dw.astype(w.dtype)

    local.defvjp(fwd, bwd)
    h_spec = _with_stage(P(dp_axes or None, None, axes), stage_axis)
    w_spec = _with_stage(P(axes, None), stage_axis)
    y_spec = _with_stage(P(dp_axes or None, axes, None), stage_axis)
    return shard_map(_staged(local, stage_axis is not None), mesh,
                     in_specs=(h_spec, w_spec),
                     out_specs=y_spec, check_rep=False)


# ---------------------------------------------------------------------------
# per-layer eligibility + dispatch
# ---------------------------------------------------------------------------

# The eligibility predicates and fallback-reason strings live in
# analysis/eligibility.py (shared with the launcher's logging, the cost
# model's discount gate and the plan doctor); re-exported here because this
# module is their historical home and the kernel dispatch reads them.
from hetu_galvatron_tpu.analysis.eligibility import (  # noqa: E402,F401
    MOE_REASON,
    T5_REASON,
    layer_overlap_reason,
    plan_overlap_reasons,
)


def make_layer_matmuls(mesh: Mesh, dp_axes: Tuple[str, ...],
                       tp_axes: Tuple[str, ...],
                       stage_axis: Optional[str] = None
                       ) -> Dict[str, Callable]:
    """The projection matmuls of one decoder layer as overlapped
    ring-decomposed fns (``matmul_fns`` for modules.apply_decoder_layer):
    column-parallel qkv/fc1 share one ag_matmul, row-parallel out/fc2 share
    one matmul_rs (the builders are shape-polymorphic), and gated MLPs use
    the shard-aligned ``fc1_pair`` instead of splitting the fused product
    globally. ``stage_axis`` builds the pp-stacked variants the compiled
    pipeline engine calls on ``[pp, ...]`` operands."""
    ag = make_ag_matmul(mesh, dp_axes, tp_axes, stage_axis)
    rs = make_matmul_rs(mesh, dp_axes, tp_axes, stage_axis)
    pair = make_ag_matmul_pair(mesh, dp_axes, tp_axes, stage_axis)
    return {"qkv": ag, "out": rs, "fc1": ag, "fc2": rs, "fc1_pair": pair}
