"""Ulysses sequence parallelism: explicit head-scatter all-to-all attention.

Capability parity with the reference's Ulysses attention
(runtime/transformer/attention_impl.py:201 ``_SeqAllToAll`` +
``UlyssesAttention``): activations arrive sequence-sharded over the sp mesh
axes (weights replicated); attention needs the full sequence, so q/k/v
all-to-all from sequence-sharded/full-heads to full-sequence/head-sharded,
run the local core, and all-to-all back.

TPU-first: the two transposes are ``jax.lax.all_to_all`` collectives inside
a ``shard_map`` — explicitly scheduled ICI all-to-alls, not whatever GSPMD
infers for a sharded softmax (the round-2 verdict flagged the implicit
lowering as a perf landmine: an inferred all-gather moves sp× more bytes
than the head-scatter a2a). The local core is swappable, so on TPU the
full-sequence attention inside the shard_map is the Pallas flash kernel.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Optional, Tuple

import jax
from jax.sharding import Mesh, PartitionSpec as P

from hetu_galvatron_tpu.models.modules import xla_sdpa


def _ulysses_local(q, k, v, *, axis, causal, local_sdpa):
    """Per-device body: [b, s_loc, N, D] -> a2a -> [b, S, N/sp, D] ->
    attention -> a2a back."""
    # scatter heads (axis 2), gather sequence (axis 1)
    q = jax.lax.all_to_all(q, axis, split_axis=2, concat_axis=1, tiled=True)
    k = jax.lax.all_to_all(k, axis, split_axis=2, concat_axis=1, tiled=True)
    v = jax.lax.all_to_all(v, axis, split_axis=2, concat_axis=1, tiled=True)
    out = local_sdpa(q, k, v, causal=causal)
    # inverse: scatter sequence, gather heads
    return jax.lax.all_to_all(out, axis, split_axis=1, concat_axis=2,
                              tiled=True)


def make_ulysses_sdpa(
    mesh: Mesh,
    sp_axes: Tuple[str, ...],
    dp_axes: Tuple[str, ...] = (),
    local_sdpa: Optional[Callable] = None,
    stage_axis: Optional[str] = None,
) -> Callable:
    """sdpa_fn for modules.apply_attention on a Ulysses layer.

    Falls back to the XLA core (GSPMD-inferred collectives) when the q or kv
    head count does not divide by the sp degree — the head-scatter a2a needs
    whole heads per device (the reference asserts the same divisibility,
    attention_impl.py:235).

    ``stage_axis`` (the compiled 1F1B engine): q/k/v carry a leading
    ``[pp, ...]`` stacked stage dim sharded on that mesh axis; the
    shard_map spans the whole mesh (full-manual) and each pp row runs its
    own stage's a2a sandwich over the sp axes."""
    if not sp_axes:
        raise ValueError("ulysses attention needs at least one sp axis")
    axis = sp_axes if len(sp_axes) > 1 else sp_axes[0]
    sp = 1
    for a in sp_axes:
        sp *= mesh.shape[a]
    spec = P(dp_axes or None, sp_axes, None, None)
    s_dim, h_dim = 1, 2
    if stage_axis is not None:
        spec = P(stage_axis, *spec)
        s_dim, h_dim = 2, 3
    core = local_sdpa or xla_sdpa

    warned = []

    def sdpa(q, k, v, *, causal=True):
        import jax.numpy as jnp

        N, K = q.shape[h_dim], k.shape[h_dim]
        # decide the path on the ORIGINAL shapes: replication must only
        # happen when the a2a path is actually taken (the fallback core
        # needs the true GQA head ratio)
        K_eff = sp if (K % sp and sp % K == 0) else K
        if N % sp or K_eff % sp or N % K_eff or q.shape[s_dim] % sp:
            if stage_axis is not None:
                return jax.vmap(lambda a, b, c: xla_sdpa(
                    a, b, c, causal=causal))(q, k, v)
            return xla_sdpa(q, k, v, causal=causal)
        if K_eff != K:
            # GQA with fewer kv heads than the sp degree: replicate kv heads
            # up to sp so the head scatter stays whole-headed (reference
            # repeat_interleave, attention_impl.py:278-417)
            rep = sp // K
            k = jnp.repeat(k, rep, axis=h_dim)
            v = jnp.repeat(v, rep, axis=h_dim)

        def run(inner):
            from jax.experimental.shard_map import shard_map

            from hetu_galvatron_tpu.ops.overlap import staged_lane

            local = partial(_ulysses_local, axis=axis, causal=causal,
                            local_sdpa=inner)
            body = staged_lane(local, stage_axis is not None)
            return shard_map(
                body,
                mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
                check_rep=False)(q, k, v)
        if core is not xla_sdpa:
            try:
                return run(core)  # e.g. flash: may reject untileable shapes
            except (ValueError, TypeError) as e:
                if not warned:
                    warned.append(True)
                    print("warning: ulysses local attention core "
                          f"({getattr(core, '__name__', core)}) failed "
                          f"({type(e).__name__}: {e}); using the XLA core",
                          flush=True)
        return run(xla_sdpa)

    return sdpa
