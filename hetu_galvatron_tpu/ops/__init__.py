from hetu_galvatron_tpu.ops.ring_attention import (  # noqa: F401
    make_ring_sdpa,
    zigzag_layout,
    zigzag_unlayout,
)
