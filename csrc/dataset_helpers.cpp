// Dataset index helpers: sample-mapping construction for the indexed GPT
// dataset.
//
// Same role as the reference's C++ dataset builder
// (runtime/datasets/megatron/helpers.cpp build_sample_idx, compiled lazily at
// startup via initialize.py:163-187): given per-document token counts, emit
// for each training sample of (seq_len + 1) tokens the (document index,
// in-document offset) where it starts, treating the corpus as one
// concatenated token stream. O(num_samples + num_docs) two-pointer walk —
// the hot one-shot loop that is painfully slow in Python for billion-token
// corpora.
//
// Build: make -C csrc libdataset_helpers.so  (g++ -O2 -shared -fPIC)

#include <cstdint>

extern "C" {

// doc_lens:   [num_docs] token count per document
// out_doc:    [num_samples] starting document index per sample
// out_offset: [num_samples] starting token offset within that document
// Returns the number of samples actually written (may be < num_samples when
// the corpus is too small).
int64_t build_sample_idx(const int64_t* doc_lens, int64_t num_docs,
                         int64_t seq_len, int64_t num_samples,
                         int64_t* out_doc, int64_t* out_offset) {
    const int64_t stride = seq_len;  // samples advance seq_len tokens
    int64_t doc = 0;
    int64_t offset = 0;
    int64_t total = 0;
    for (int64_t d = 0; d < num_docs; ++d) total += doc_lens[d];

    int64_t written = 0;
    int64_t pos = 0;
    for (int64_t s = 0; s < num_samples; ++s) {
        if (pos + seq_len + 1 > total) break;
        out_doc[written] = doc;
        out_offset[written] = offset;
        ++written;
        // advance the two-pointer walk by `stride` tokens
        int64_t remaining = stride;
        while (remaining > 0 && doc < num_docs) {
            const int64_t avail = doc_lens[doc] - offset;
            if (avail > remaining) {
                offset += remaining;
                remaining = 0;
            } else {
                remaining -= avail;
                ++doc;
                offset = 0;
            }
        }
        pos += stride;
    }
    return written;
}

}  // extern "C"
