// Knapsack dynamic-programming core for the strategy search.
//
// Same recurrence as the reference's pybind11 core (csrc/dp_core.cpp:24-121
// dynamic_programming_core): f[v][s] = min_si f[v - mem(i,s)][si]
// + inter(i,si,s) + intra(i,s), walked back through the mark table, with the
// vocab-layer memory/time folded in at the budget boundary. Exposed through a
// plain C ABI for ctypes (this image has no pybind11); one (other_mem,
// other_time) pair per call instead of the reference's legacy map-of-vtp.
//
// Build: make -C csrc  (g++ -O2 -shared -fPIC)

#include <algorithm>
#include <cstdint>
#include <limits>
#include <vector>

extern "C" {

// Returns 0 on success, 1 when no in-budget assignment exists.
// v:      [layer_num x strategy_num] int32 per-layer memory cost (MB)
// inter:  [layer_num x strategy_num x strategy_num] transition costs
// intra:  [layer_num x strategy_num] per-layer time costs
// mark:   [layer_num x max_mem x strategy_num] int32 workspace
// f:      [max_mem x strategy_num] double workspace (zero-initialised)
// res:    [layer_num] int32 output strategy indices
int dp_solve(int layer_num, int max_mem, int strategy_num,
             const int32_t* v, const double* inter, const double* intra,
             int other_mem, double other_time,
             int32_t* mark, double* f, int32_t* res,
             double* total_cost_out, int* remaining_mem_out) {
    const double INF = std::numeric_limits<double>::infinity();

    for (int i = 0; i < layer_num; ++i) {
        for (int m = max_mem - 1; m >= 0; --m) {
            for (int s = 0; s < strategy_num; ++s) {
                const int need = v[i * strategy_num + s];
                if (m < need) {
                    mark[(int64_t)i * max_mem * strategy_num +
                         (int64_t)m * strategy_num + s] = -1;
                    f[m * strategy_num + s] = INF;
                    continue;
                }
                const double* prev = f + (int64_t)(m - need) * strategy_num;
                const double* tr =
                    inter + (int64_t)i * strategy_num * strategy_num;
                double best = INF;
                int best_si = 0;
                for (int si = 0; si < strategy_num; ++si) {
                    const double c = prev[si] + tr[si * strategy_num + s];
                    if (c < best) {
                        best = c;
                        best_si = si;
                    }
                }
                mark[(int64_t)i * max_mem * strategy_num +
                     (int64_t)m * strategy_num + s] = best_si;
                f[m * strategy_num + s] = best + intra[i * strategy_num + s];
            }
        }
    }

    int budget = max_mem - 1 - other_mem;
    if (budget < 0) {
        *total_cost_out = INF;
        *remaining_mem_out = -1;
        return 1;
    }
    const double* last = f + (int64_t)budget * strategy_num;
    int next_index = (int)std::distance(
        last, std::min_element(last, last + strategy_num));
    int next_v = budget;
    double total = last[next_index];
    if (!(total < INF)) {
        *total_cost_out = INF;
        *remaining_mem_out = -1;
        return 1;
    }
    total += other_time;

    res[layer_num - 1] = next_index;
    for (int i = layer_num - 1; i > 0; --i) {
        const int cur = next_index;
        next_index = mark[(int64_t)i * max_mem * strategy_num +
                          (int64_t)next_v * strategy_num + next_index];
        next_v -= v[i * strategy_num + cur];
        res[i - 1] = next_index;
    }
    *total_cost_out = total;
    *remaining_mem_out = next_v - v[next_index];
    return 0;
}

}  // extern "C"
